"""Grandfathered-finding baseline (``graftlint_baseline@1``).

The committed baseline ships with ZERO entries — every true positive
the first full run surfaced was fixed in the PR that introduced the
linter — but the machinery exists so a future emergency can land with
a grandfathered finding instead of a deleted rule, and so the
baseline's contents are reviewable in diffs (each entry carries the
rule, path, and offending line text, not just a hash).

Fingerprints hash the rule, path, and *whitespace-normalized line
text* — NOT the line number — so unrelated edits above a grandfathered
site don't churn the file.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Set, Tuple

from .base import Finding

FORMAT = "graftlint_baseline@1"


def fingerprint(finding: Finding, line_text: str) -> str:
    norm = " ".join(line_text.split())
    blob = f"{finding.rule}|{finding.path}|{norm}"
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]


def load(path: str) -> Set[str]:
    if not os.path.exists(path):
        return set()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("format") != FORMAT:
        raise ValueError(
            f"{path}: expected format {FORMAT!r}, "
            f"got {data.get('format')!r}"
        )
    return {e["fingerprint"] for e in data.get("entries", [])}


def write(path: str, items: List[Tuple[Finding, str]]) -> None:
    """``items`` pairs each finding with its source line text."""
    entries = [
        {
            "fingerprint": fingerprint(f, line),
            "rule": f.rule,
            "path": f.path,
            "line_text": " ".join(line.split()),
        }
        for f, line in items
    ]
    entries.sort(key=lambda e: (e["path"], e["rule"], e["fingerprint"]))
    data: Dict = {"format": FORMAT, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
