"""graftlint driver: discover → parse → rules → suppressions →
baseline → verdict.

Import side effects: importing this module registers every rule
module (the ``RULE_REGISTRY`` population is the import), nothing
else — no jax, no package modules.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# graftlint: disable=unused-import -- importing populates RULE_REGISTRY
from . import (
    rules_env, rules_hygiene, rules_numerics, rules_staging,
    rules_tracer,
)
from .base import Finding, LintContext, RULE_REGISTRY
from .baseline import fingerprint as baseline_fingerprint
from .baseline import load as baseline_load
from .envmodel import parse_env_registry, parse_fault_sites
from .source import SourceFile, discover_files, load_source

# Rules the driver itself emits (suppressions / parse failures) — part
# of the known-rule set so directives can reference them.
_DRIVER_RULES = ("bad-suppression", "parse-error")


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # errors
    notes: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    baselined: int = 0
    elapsed_s: float = 0.0
    files: int = 0
    # (finding, source line text) for --write-baseline
    raw_pairs: List[Tuple[Finding, str]] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings


def known_rule_names() -> Tuple[str, ...]:
    return tuple(sorted(RULE_REGISTRY)) + _DRIVER_RULES


def run_lint(
    root: str,
    paths: Optional[Sequence[str]] = None,
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> LintResult:
    """Run the checker.

    ``paths`` overrides the default fileset (scratch-file checks in
    tests and the acceptance gate); ``rules`` restricts to named rules
    (fixture tests); ``baseline_path`` points at the committed
    grandfather file (zero entries in this repo).
    """
    t0 = time.perf_counter()
    result = LintResult()
    known = set(known_rule_names())
    if rules is not None:
        bad = sorted(set(rules) - set(RULE_REGISTRY))
        if bad:
            raise ValueError(f"unknown rule(s): {', '.join(bad)}")
    active = {
        name: cls() for name, cls in RULE_REGISTRY.items()
        if rules is None or name in rules
    }

    ctx = LintContext(root=root)
    # Explicit-paths runs are PARTIAL: cross-file "declared but
    # unused" checks can't conclude anything and skip themselves.
    ctx.shared["partial_run"] = paths is not None
    ctx.env_registry = parse_env_registry(root)
    sites, site_lines = parse_fault_sites(root)
    ctx.fault_sites = sites
    ctx.shared["fault_site_lines"] = site_lines

    files = list(paths) if paths is not None else discover_files(root)
    result.files = len(files)
    sources: Dict[str, SourceFile] = {}
    collected: List[Tuple[Finding, SourceFile]] = []
    for path in files:
        src = load_source(path, root, known)
        sources[src.rel] = src
        if src.parse_error is not None:
            collected.append((src.parse_error, src))
            continue
        for f in src.suppression_findings:
            collected.append((f, src))
        for rule in active.values():
            for f in rule.visit(src, ctx):
                collected.append((f, src))
    for rule in active.values():
        for f in rule.finalize(ctx):
            collected.append((f, sources.get(f.path)))

    baseline = (
        baseline_load(baseline_path) if baseline_path else set()
    )
    for f, src in collected:
        line_text = ""
        if src is not None and 0 < f.line <= len(src.lines):
            line_text = src.lines[f.line - 1]
        if src is not None and f.rule in src.suppressions.get(
            f.line, ()
        ):
            result.suppressed += 1
            continue
        if f.severity == "note":
            result.notes.append(f)
            continue
        result.raw_pairs.append((f, line_text))
        if baseline and baseline_fingerprint(f, line_text) in baseline:
            result.baselined += 1
            continue
        result.findings.append(f)

    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.notes.sort(key=lambda f: (f.path, f.line, f.rule))
    result.elapsed_s = time.perf_counter() - t0
    return result


def default_fileset(root: str) -> List[str]:
    return discover_files(root)
