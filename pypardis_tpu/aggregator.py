"""Cross-partition cluster-label aggregation.

API-parity re-implementation of the reference merge layer
(``/root/reference/dbscan/aggregator.py:5-73``): a ``ClusterAggregator``
whose ``__add__`` doubles as seqOp and combOp, mapping partition-level
labels ("part:cluster[*]") to dense global ids with min-id-wins merge
semantics (aggregator.py:45) and the noise / non-core skip rule
(aggregator.py:38-40, README.md:27-29 — border points reachable from
multiple clusters must not cause cluster merges).

``ClusterAggregator`` is the compatibility surface (faithful to the
reference, including its O(cluster size) dict-walk absorb).  The TPU hot
path doesn't use it — labels merge in-graph inside
``pypardis_tpu.parallel.sharded``.  :class:`UnionFind` is the array-based
host-side edge resolver backing the out-of-graph merge utilities.
"""

from __future__ import annotations

import sys
from collections import defaultdict


def default_value():
    """Sentinel for unmapped labels (aggregator.py:5-6, sys.maxint → maxsize)."""
    return sys.maxsize


class ClusterAggregator:
    """Merge partition-level labels into global cluster ids.

    State mirrors the reference (aggregator.py:15-17): ``fwd`` maps
    partition-level label → global id, ``rev`` maps global id → set of
    labels, ``next_global_id`` is the fresh-id counter.
    """

    def __init__(self):
        self.fwd = defaultdict(default_value)
        self.rev = defaultdict(set)
        self.next_global_id = 0

    def __add__(self, other):
        """seqOp/combOp dual dispatch (aggregator.py:19-63).

        With another aggregator: replay its ``rev`` entries.  With an
        ``(index, labels)`` tuple: skip if the point's first label is
        noise or non-core, else union all its labels under the minimum
        existing global id (creating a fresh id when none exists).
        """
        if isinstance(other, ClusterAggregator):
            for item in other.rev.items():
                self + item
            return self

        _index, pl_ids = other
        new_ids = set(pl_ids)
        first = next(iter(new_ids))
        # Noise ('-1') and non-core ('*'-suffixed) points never create or
        # merge clusters (aggregator.py:38-40).
        if "-1" in first or "*" in first:
            return self

        global_id = self.next_global_id
        for new_id in new_ids:
            if new_id in self.fwd:
                global_id = min(global_id, self.fwd[new_id])
        if global_id == self.next_global_id:
            self.next_global_id += 1
        else:
            overlaps = {
                self.fwd[new_id] for new_id in new_ids if new_id in self.fwd
            }
            for gl_id in overlaps:
                if gl_id != global_id:
                    for pl_id in self.rev[gl_id]:
                        self.fwd[pl_id] = global_id
                        self.rev[global_id].add(pl_id)
                    del self.rev[gl_id]
        for new_id in new_ids:
            self[new_id] = global_id
        return self

    def __setitem__(self, a, b):
        """fwd[a] = b and record a under rev[b] (aggregator.py:66-73)."""
        self.fwd[a] = b
        self.rev[b].add(a)

    def __len__(self):
        return len(self.rev)


class UnionFind:
    """Array-based union-find: min-id linking with path compression.

    Min-id linking is load-bearing — roots are always the minimum id of
    their component, matching aggregator.py:45's downward merges.  Used
    by the host-side merge utilities (``pypardis_tpu.parallel.merge``)
    to resolve label-equivalence edge tables in near-linear time, where
    the reference used a driver-memory-bound dict aggregation
    (README.md:60).
    """

    __slots__ = ("parent",)

    def __init__(self, n: int):
        import numpy as np

        self.parent = np.arange(n, dtype=np.int64)

    def find(self, x: int) -> int:
        parent = self.parent
        root = x
        while parent[root] != root:
            root = parent[root]
        # Path compression.
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return
        # Min-id wins, matching aggregator.py:45's downward merges.
        if ra < rb:
            self.parent[rb] = ra
        else:
            self.parent[ra] = rb

    def roots(self):
        """Return the fully-compressed parent array (vectorized)."""
        import numpy as np

        parent = self.parent
        while True:
            grand = parent[parent]
            if np.array_equal(grand, parent):
                return parent
            parent = grand
