"""Metrics registry: counters, gauges, timing aggregates, one key schema.

Absorbs what used to live in three places — ``PhaseTimer.as_dict()``,
the sharded path's ``stats`` dicts, and ``DBSCAN.metrics_`` — so every
number a run produces is reachable under one dotted key namespace and
mergeable across runs (bench loops, retries, multi-fit sweeps).
"""

from __future__ import annotations

import re
from typing import Dict, Union

from .export import Histogram

_KEY_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")

Number = Union[int, float]


def _py(value):
    """Coerce numpy scalars (and anything with ``.item()``) to plain
    Python numbers so every registry dump is json-serializable."""
    if isinstance(value, (int, float, str, bool)) or value is None:
        return value
    item = getattr(value, "item", None)
    if callable(item):
        return item()
    return value


def sanitize_segment(s) -> str:
    """Coerce an arbitrary string into one valid key segment (for call
    sites that build keys from user-ish names, e.g. phase labels)."""
    out = re.sub(r"[^a-z0-9_]", "_", str(s).lower())
    return out or "x"


def validate_key(key: str) -> str:
    if not isinstance(key, str) or not _KEY_RE.match(key):
        raise ValueError(
            f"metric key {key!r} violates the schema: lowercase dotted "
            f"segments of [a-z0-9_]"
        )
    return key


class MetricsRegistry:
    """Counters (monotonic adds), gauges (last write wins), and timing
    aggregates (count / total / min / max seconds).

    >>> reg = MetricsRegistry()
    >>> reg.inc("events.retry.restage")
    >>> reg.set("sharded.halo_factor", 0.18)
    >>> reg.observe("phase.cluster", 1.25)
    >>> reg.as_dict()["gauges"]["sharded.halo_factor"]
    0.18

    ``merge`` combines two registries with the natural semantics per
    type: counters add, gauges take the other's value (it is newer),
    timing aggregates pool their samples.
    """

    def __init__(self):
        self._counters: Dict[str, Number] = {}
        self._gauges: Dict[str, object] = {}
        self._timings: Dict[str, Dict[str, float]] = {}
        self._hists: Dict[str, Histogram] = {}
        # Optional streaming sink (obs.flight.FlightRecorder): every
        # write also lands in the JSONL file, so a killed run's gauges
        # and phase timings are recoverable from disk.
        self.sink = None

    # -- write surface ----------------------------------------------------

    def inc(self, key: str, value: Number = 1) -> None:
        validate_key(key)
        self._counters[key] = self._counters.get(key, 0) + _py(value)
        if self.sink is not None:
            self.sink.count(key, value)

    def set(self, key: str, value) -> None:
        validate_key(key)
        self._gauges[key] = _py(value)
        if self.sink is not None:
            self.sink.gauge(key, value)

    def observe(self, key: str, seconds: float) -> None:
        validate_key(key)
        s = float(_py(seconds))
        if self.sink is not None:
            self.sink.timing(key, s)
        t = self._timings.get(key)
        if t is None:
            self._timings[key] = {
                "count": 1, "total_s": s, "min_s": s, "max_s": s,
            }
        else:
            t["count"] += 1
            t["total_s"] += s
            t["min_s"] = min(t["min_s"], s)
            t["max_s"] = max(t["max_s"], s)
        # Timings double as histograms (ms) so exporters can show
        # windowed phase-latency percentiles mid-run.  No sink forward:
        # the tm record above already carries the sample to the flight.
        h = self._hists.get(key)
        if h is None:
            h = self._hists[key] = Histogram()
        h.observe(s * 1e3)

    def hist(self, key: str, window_s: float = None) -> Histogram:
        """Get-or-create the bounded histogram under ``key`` (unit: ms).

        This is the structure sustained serving migrates its latency
        tracking onto — O(buckets) memory forever, windowed p50/p99.
        """
        h = self._hists.get(key)
        if h is None:
            validate_key(key)
            h = self._hists[key] = Histogram(window_s=window_s)
        return h

    def observe_ms(self, key: str, value_ms: float) -> None:
        """Record one latency sample (milliseconds) into the histogram
        under ``key`` and forward it to the sink's ``hist`` channel."""
        self.hist(key).observe(value_ms)
        if self.sink is not None:
            hs = getattr(self.sink, "hist", None)
            if hs is not None:
                hs(key, float(value_ms))

    def load_hist(self, key: str, snap: dict) -> None:
        """Install a histogram rebuilt from a snapshot dict (flight
        replay / fleet merge), pooling into any existing one."""
        validate_key(key)
        h = Histogram.from_snapshot(snap)
        mine = self._hists.get(key)
        if mine is None:
            self._hists[key] = h
        else:
            mine.merge_from(h)

    # -- read surface -----------------------------------------------------

    def counter(self, key: str, default: Number = 0) -> Number:
        return self._counters.get(key, default)

    def gauge(self, key: str, default=None):
        return self._gauges.get(key, default)

    def counters_with_prefix(self, prefix: str) -> Dict[str, Number]:
        return {
            k: v for k, v in self._counters.items() if k.startswith(prefix)
        }

    def gauges_with_prefix(self, prefix: str) -> Dict[str, object]:
        return {
            k: v for k, v in self._gauges.items() if k.startswith(prefix)
        }

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """Fold ``other`` into this registry (see class docstring)."""
        for k, v in other._counters.items():
            self._counters[k] = self._counters.get(k, 0) + v
        self._gauges.update(other._gauges)
        for k, t in other._timings.items():
            mine = self._timings.get(k)
            if mine is None:
                self._timings[k] = dict(t)
            else:
                mine["count"] += t["count"]
                mine["total_s"] += t["total_s"]
                mine["min_s"] = min(mine["min_s"], t["min_s"])
                mine["max_s"] = max(mine["max_s"], t["max_s"])
        for k, h in other._hists.items():
            mine_h = self._hists.get(k)
            if mine_h is None:
                self._hists[k] = h.clone()
            else:
                mine_h.merge_from(h)
        return self

    def as_dict(self) -> Dict[str, dict]:
        """One json-serializable dump: ``{"counters", "gauges",
        "timings", "hists"}`` — timings carry count/total/min/max/mean
        seconds; hists are :meth:`Histogram.snapshot` dicts."""
        timings = {}
        for k, t in self._timings.items():
            d = dict(t)
            d["mean_s"] = d["total_s"] / max(d["count"], 1)
            timings[k] = d
        return {
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "timings": timings,
            "hists": {k: h.snapshot() for k, h in self._hists.items()},
        }
