"""Unified observability: metrics registry, span tracing, run reports.

The reference shipped a dead ``LOGGING`` flag and nothing else (reference
dbscan.py:9, SURVEY §5).  This repro accreted three disconnected
surfaces — ``PhaseTimer`` wall times, ``log_phase`` lines, and ad-hoc
``stats`` dicts riding out of the sharded path — none of which shared a
schema or an export path.  This package is the single replacement:

* :class:`MetricsRegistry` — counters / gauges / timing aggregates
  under one dotted-key schema (``phase.cluster``, ``sharded.halo_factor``,
  ``events.retry.restage``, ...);
* :class:`Tracer` / spans — nestable wall-time spans with the
  ``sync_on`` device-sync semantics lifted from ``PhaseTimer``,
  exportable as Chrome-trace / Perfetto JSON (``traceEvents``)
  alongside the existing ``jax.profiler`` hook;
* :class:`RunRecorder` — one object per fit holding the registry, the
  tracer, and the event log (restage / pair-budget / halo-capacity /
  merge-round ladder triggers with their exceptions); library layers
  reach the active one via :func:`current` so no signature anywhere
  threads a telemetry handle;
* :func:`build_run_report` / :func:`format_summary` — the schema'd
  ``DBSCAN.report()`` dict and its one-screen human rendering;
* :class:`~pypardis_tpu.obs.flight.FlightRecorder` / :func:`replay` —
  the crash-safe append-only JSONL sink (opt-in via
  ``DBSCAN(flight=...)`` / ``PYPARDIS_FLIGHT``) and its post-mortem
  reconstruction: a killed run's file still yields a Chrome trace and
  a partial report (format ``pypardis_tpu/flight@1``);
* :class:`~pypardis_tpu.obs.resources.ResourceSampler` — the per-fit
  watermark thread behind ``report()["resources"]`` (peak host RSS /
  device live bytes / staging-pool bytes);
* :func:`heartbeat` — opt-in per-round progress + ETA lines
  (``PYPARDIS_HEARTBEAT``) on the stepped / chained / global-Morton
  round loops;
* :class:`~pypardis_tpu.obs.export.Histogram` /
  :func:`attach_exporters` — the live plane: bounded log-bucket latency
  histograms with windowed p50/p99 (what sustained serving tracks
  latency on), a periodic JSONL snapshot emitter
  (``PYPARDIS_METRICS_SNAPSHOT``), and an opt-in OpenMetrics scrape
  endpoint (``PYPARDIS_METRICS_PORT``) live during fits and load runs;
* :class:`~pypardis_tpu.obs.fleet.FleetReplay` — N per-process flight
  files aligned onto one timeline: per-host Chrome-trace lanes, a
  merged JSONL, a fleet-level partial report (``replay()`` on a
  directory dispatches here); ``scripts/monitor.py`` live-tails either.

Key schema: lowercase dotted segments ``[a-z0-9_]+(.[a-z0-9_]+)*``.
Reserved prefixes: ``phase.`` (timings, seconds), ``events.`` (counters,
one per recorded event kind), ``sharded.`` / ``run.`` (gauges from the
execution paths), ``compile.`` (first-compile markers), ``resources.``
(watermark gauges), ``gm.`` (global-Morton ring/fixpoint telemetry).
"""

from .recorder import RunRecorder, current, event, span, use_recorder
from .registry import MetricsRegistry
from .report import REPORT_SCHEMA, build_run_report, format_summary
from .trace import Tracer
from .export import Histogram, attach_exporters, last_http_port
from .flight import (
    FLIGHT_SCHEMA,
    FlightRecorder,
    FlightReplay,
    flight_note,
    heartbeat,
    open_flight,
    replay,
)
from .fleet import FleetReplay, fleet_replay
from .resources import ResourceSampler

__all__ = [
    "MetricsRegistry",
    "Tracer",
    "RunRecorder",
    "current",
    "use_recorder",
    "span",
    "event",
    "build_run_report",
    "format_summary",
    "REPORT_SCHEMA",
    "FLIGHT_SCHEMA",
    "FlightRecorder",
    "FlightReplay",
    "FleetReplay",
    "fleet_replay",
    "Histogram",
    "attach_exporters",
    "last_http_port",
    "flight_note",
    "heartbeat",
    "open_flight",
    "replay",
    "ResourceSampler",
]
