"""Crash-safe flight recorder: an append-only JSONL telemetry sink.

The rest of :mod:`pypardis_tpu.obs` is in-memory and post-hoc — a run
that dies mid-fit (host OOM in the streaming sort, a too-small ring
``btcap``, a hung fixpoint round, a SIGKILL from a watchdog) leaves
*nothing*: ``report()``/``export_trace()`` need a live recorder in a
live process.  The flight recorder is the durable complement, the same
role Dask's performance-report/event-log machinery and Ray's timeline
files play for their schedulers: every span open/close, phase timing,
gauge write, ladder-retry event, heartbeat, staging note, and resource
sample is appended to a JSONL file and flushed within one flush
interval (``PYPARDIS_FLIGHT_FLUSH_S``, default 0.25s; span opens,
closes, and events flush eagerly), so a killed run leaves a parseable
post-mortem on disk.

Crash semantics are deliberate:

* a span an exception unwinds through is **left open in the file** (no
  close record) — the same signature a SIGKILL leaves — so the last
  open span marks where the run died; the in-memory tracer still
  closes it, keeping ``export_trace()`` on the live model intact;
* a run that ends (ok or error) appends one ``fin`` record; a file
  without it was killed outright.

:func:`replay` reconstructs the observable state from the file alone —
a Chrome trace (open spans rendered to the last record's timestamp and
tagged ``unclosed``), the metrics registry, the event log, and a
partial ``run_report`` — which is what ``make flight-check`` exercises
by SIGKILLing a fit mid-run.

File format (one JSON object per line, format version
``pypardis_tpu/flight@1``): ``k`` discriminates the record kind —
``header`` (schema/pid/params), ``so``/``sc`` (span open/close by
``id``), ``sx`` (pre-measured complete span), ``ev`` (recorder event),
``g``/``c``/``tm`` (gauge/counter/timing write), ``h`` (bounded
latency-histogram snapshot, rate-limited per key; the last one per key
wins on replay), ``rs`` (resource sample), ``hb`` (heartbeat), ``note``
(staging and other annotations), ``fin`` (run end).  All ``t`` fields
are seconds relative to the run recorder's tracer epoch.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .recorder import current
from .trace import _jsonable
from ..utils import envreg

FLIGHT_SCHEMA = "pypardis_tpu/flight@1"

_FLUSH_DEFAULT_S = 0.25

# Per-process sequence for directory-mode file names: two fits in the
# same second must not collide.
_seq_lock = threading.Lock()
_seq = [0]


def _next_seq() -> int:
    with _seq_lock:
        _seq[0] += 1
        return _seq[0]


class FlightRecorder:
    """One append-only JSONL sink, attached to one :class:`RunRecorder`.

    Thread-safe (the resource sampler writes from its own thread).
    ``flush_interval_s`` bounds how stale the on-disk tail can be; a
    plain ``flush()`` (user buffer -> OS) is enough for the SIGKILL
    contract — the process dies, the kernel keeps the written bytes.
    """

    def __init__(self, path: str, flush_interval_s: Optional[float] = None):
        self.path = path
        if flush_interval_s is None:
            flush_interval_s = float(
                envreg.raw("PYPARDIS_FLIGHT_FLUSH_S", _FLUSH_DEFAULT_S)
            )
        self._flush_every = max(float(flush_interval_s), 0.0)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        self._last_flush = 0.0
        self._finished = False
        self.records = 0
        self._hists: Dict[str, object] = {}
        self._hist_last_emit: Dict[str, float] = {}

    # -- wiring ------------------------------------------------------------

    def set_epoch(self, epoch_s: float) -> None:
        """Adopt the attached tracer's epoch so span/record timestamps
        share one clock."""
        self._epoch = float(epoch_s)

    def _t(self, abs_s: Optional[float] = None) -> float:
        base = time.perf_counter() if abs_s is None else abs_s
        return round(base - self._epoch, 6)

    def _emit(self, rec: Dict, urgent: bool = False) -> None:
        try:
            line = json.dumps(rec, separators=(",", ":"), default=str)
        except (TypeError, ValueError):
            return  # a sink must never take the fit down
        with self._lock:
            f = self._f
            if f is None or f.closed:
                return
            f.write(line + "\n")
            self.records += 1
            now = time.monotonic()
            if urgent or now - self._last_flush >= self._flush_every:
                f.flush()
                self._last_flush = now

    @staticmethod
    def _attrs(attrs: Dict) -> Dict:
        return {k: _jsonable(v) for k, v in attrs.items()}

    # -- record kinds ------------------------------------------------------

    def header(self, **fields) -> None:
        self._emit(
            {
                "k": "header",
                "schema": FLIGHT_SCHEMA,
                "pid": os.getpid(),
                "t_unix": round(time.time(), 3),
                **self._attrs(fields),
                **(
                    {"params": fields["params"]}
                    if isinstance(fields.get("params"), dict)
                    else {}
                ),
            },
            urgent=True,
        )

    def span_open(self, sid, name, t0_s, depth, attrs) -> None:
        self._emit(
            {
                "k": "so",
                "id": int(sid),
                "name": name,
                "t": self._t(t0_s),
                "depth": int(depth),
                "a": self._attrs(attrs),
            },
            urgent=True,
        )

    def span_close(self, sid, name, t0_s, dur_s, attrs) -> None:
        self._emit(
            {
                "k": "sc",
                "id": int(sid),
                "name": name,
                "t": self._t(t0_s),
                "dur": round(float(dur_s), 6),
                "a": self._attrs(attrs),
            },
            urgent=True,
        )

    def span_complete(self, name, t0_s, dur_s, attrs) -> None:
        self._emit(
            {
                "k": "sx",
                "name": name,
                "t": self._t(t0_s),
                "dur": round(float(dur_s), 6),
                "a": self._attrs(attrs),
            },
            urgent=True,
        )

    def event(self, kind: str, fields: Dict) -> None:
        self._emit(
            {"k": "ev", "kind": kind, "t": self._t(),
             "f": self._attrs(fields)},
            urgent=True,
        )

    def gauge(self, key: str, value) -> None:
        self._emit({"k": "g", "key": key, "v": _jsonable(value),
                    "t": self._t()})

    def count(self, key: str, value) -> None:
        self._emit({"k": "c", "key": key, "v": _jsonable(value),
                    "t": self._t()})

    def timing(self, key: str, seconds: float) -> None:
        self._emit({"k": "tm", "key": key, "s": round(float(seconds), 6),
                    "t": self._t()})

    def sample(self, **fields) -> None:
        self._emit({"k": "rs", "t": self._t(), **self._attrs(fields)})

    def heartbeat(self, stage: str, done: int, total: int,
                  eta_s: float) -> None:
        self._emit(
            {"k": "hb", "stage": stage, "done": int(done),
             "total": int(total), "eta_s": round(float(eta_s), 3),
             "t": self._t()}
        )

    def note(self, kind: str, fields: Dict) -> None:
        self._emit({"k": "note", "kind": kind, "t": self._t(),
                    **self._attrs(fields)})

    def hist(self, key: str, value_ms: float) -> None:
        """One latency observation on the ``key`` histogram.

        Per-observation records would put the O(requests) cost this
        metric type exists to kill back on disk, so the recorder
        aggregates into its own bounded histogram and emits a compact
        ``h`` snapshot record at most once per flush interval per key
        (plus a final snapshot from :meth:`finish`).
        """
        from .export import Histogram

        h = self._hists.get(key)
        if h is None:
            h = self._hists[key] = Histogram()
        h.observe(value_ms)
        now = time.monotonic()
        gap = max(self._flush_every, 0.05)
        if now - self._hist_last_emit.get(key, 0.0) < gap:
            return
        self._hist_last_emit[key] = now
        self._emit({"k": "h", "key": key, "t": self._t(),
                    "snap": h.snapshot()})

    def finish(self, status: str, **fields) -> None:
        """Terminal record — first call wins (the error path writes
        ``status="error"`` before the generic close writes ``"ok"``)."""
        if self._finished:
            return
        self._finished = True
        for key, h in self._hists.items():
            self._emit({"k": "h", "key": key, "t": self._t(),
                        "snap": h.snapshot()})
        self._emit(
            {"k": "fin", "status": status, "t": self._t(),
             **self._attrs(fields)},
            urgent=True,
        )

    def close(self) -> None:
        with self._lock:
            f = self._f
            if f is None or f.closed:
                return
            try:
                f.flush()
            finally:
                f.close()


def open_flight(spec=None) -> Optional[FlightRecorder]:
    """Resolve the opt-in to a :class:`FlightRecorder`, or None.

    ``spec``: a ``*.jsonl`` file path (appended to), any other string
    (a directory — one fresh ``flight-<pid>-<stamp>-<seq>.jsonl`` per
    fit), or None to defer to the ``PYPARDIS_FLIGHT`` env var (same
    meanings; unset/empty disables).
    """
    if spec is None:
        spec = envreg.raw("PYPARDIS_FLIGHT")
    if not spec:
        return None
    spec = str(spec)
    # Multi-process fleet: every process records its OWN file — a
    # shared file path would interleave raw JSONL appends from N
    # writers.  A file spec gains a rank infix (the directory is the
    # shared store, so ``obs.replay(dir)`` merges the set); a directory
    # spec gains the rank in the generated name (pids alone collide
    # across hosts of a real pod).
    from ..parallel import dist

    rank = dist.process_index() if dist.is_distributed() else None
    if spec.endswith(".jsonl"):
        if rank is not None:
            spec = "%s.p%02d.jsonl" % (spec[: -len(".jsonl")], rank)
        d = os.path.dirname(spec)
        if d:
            os.makedirs(d, exist_ok=True)
        return FlightRecorder(spec)
    os.makedirs(spec, exist_ok=True)
    name = "flight-%s%d-%s-%d.jsonl" % (
        "" if rank is None else "r%02d-" % rank,
        os.getpid(), time.strftime("%Y%m%d-%H%M%S"), _next_seq()
    )
    return FlightRecorder(os.path.join(spec, name))


def flight_note(kind: str, **fields) -> None:
    """Append an annotation record to the current fit's flight file, if
    one is attached — the no-recorder/no-flight case is free (library
    layers call this unconditionally, e.g. the staging economy)."""
    fl = getattr(current(), "flight", None)
    if fl is not None:
        fl.note(kind, fields)


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------

_HB_LAST: Dict[str, float] = {}


def heartbeat(stage: str, done: int, total: int, t0_s: float) -> None:
    """Per-round progress with a rounds-remaining estimate.

    Always lands in the flight file when one is attached; emits an
    opt-in log line when ``PYPARDIS_HEARTBEAT`` is set (its float value
    is the minimum seconds between lines per stage — ``1`` means at
    most one line per second; the final round always logs).  Wired into
    the stepped round batches, the chained partition loop, and the
    global-Morton ring/fixpoint rounds.
    """
    now = time.perf_counter()
    elapsed = now - t0_s
    done, total = int(done), int(total)
    remaining = max(total - done, 0)
    eta = (elapsed / done) * remaining if done > 0 else -1.0
    fl = getattr(current(), "flight", None)
    if fl is not None:
        fl.heartbeat(stage, done, total, eta)
    env = envreg.raw("PYPARDIS_HEARTBEAT")
    if not env or env in ("0", "false"):
        return
    try:
        min_gap = float(env)
    except ValueError:
        min_gap = 0.0
    last = _HB_LAST.get(stage)
    if last is not None and now - last < min_gap and done < total:
        return
    _HB_LAST[stage] = now
    from ..utils import log as _log

    if not _log.get_logger().handlers:
        _log.enable()
    _log.get_logger().info(
        "heartbeat %s %d/%d rounds, elapsed %.1fs, eta %.1fs",
        stage, done, total, elapsed, max(eta, 0.0),
    )


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------


class FlightReplay:
    """The observable state of a (possibly killed) run, reconstructed
    from its flight file alone.

    ``open_spans`` are the spans the run died inside (opened, never
    closed — a SIGKILL or an exception unwinding); ``complete`` is True
    iff a terminal ``fin`` record was written; ``status`` is its
    ``ok``/``error`` value (None for a killed run).
    """

    def __init__(self, path: str):
        from .recorder import RunRecorder

        self.path = path
        self.header: Dict = {}
        self.status: Optional[str] = None
        self.complete = False
        self.records = 0
        self.bad_lines = 0
        self.open_spans: List[Dict] = []
        self.heartbeats: Dict[str, Dict] = {}
        rec = RunRecorder()
        rec.tracer.epoch_s = 0.0
        self.recorder = rec
        open_map: Dict[int, Dict] = {}
        hist_last: Dict[str, Dict] = {}
        last_t = 0.0
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                # A SIGKILL can truncate the final line mid-write; any
                # earlier corruption is counted, not fatal — a
                # post-mortem reader salvages what parses.
                self.bad_lines += 1
                continue
            self.records += 1
            t = float(r.get("t", last_t) or 0.0)
            last_t = max(last_t, t)
            k = r.get("k")
            try:
                if k == "header":
                    self.header = r
                elif k == "so":
                    open_map[int(r["id"])] = r
                elif k == "sc":
                    open_map.pop(int(r["id"]), None)
                    rec.tracer.add_complete(
                        r.get("name", "?"), t, float(r.get("dur", 0.0)),
                        **(r.get("a") or {})
                    )
                    last_t = max(last_t, t + float(r.get("dur", 0.0)))
                elif k == "sx":
                    rec.tracer.add_complete(
                        r.get("name", "?"), t, float(r.get("dur", 0.0)),
                        **(r.get("a") or {})
                    )
                    last_t = max(last_t, t + float(r.get("dur", 0.0)))
                elif k == "ev":
                    rec.metrics.inc("events." + str(r.get("kind")))
                    if len(rec.events) < rec.MAX_EVENTS:
                        rec.events.append(
                            {"kind": r.get("kind"), "t_s": t,
                             **(r.get("f") or {})}
                        )
                elif k == "g":
                    rec.metrics.set(r["key"], r.get("v"))
                elif k == "c":
                    # events.* counter bumps are duplicates of the
                    # (urgent, authoritative) "ev" records — skip them
                    # so replayed event counts aren't doubled.
                    if not str(r["key"]).startswith("events."):
                        rec.metrics.inc(r["key"], r.get("v", 1))
                elif k == "tm":
                    rec.metrics.observe(r["key"], float(r.get("s", 0.0)))
                elif k == "h":
                    # Histogram snapshots supersede each other (each
                    # carries the full lifetime counts) — keep the last
                    # per key, installed at end-of-parse below.
                    hist_last[str(r["key"])] = r.get("snap") or {}
                elif k == "hb":
                    self.heartbeats[str(r.get("stage"))] = {
                        "done": int(r.get("done", 0) or 0),
                        "total": int(r.get("total", 0) or 0),
                        "eta_s": float(r.get("eta_s", -1.0) or 0.0),
                        "t_s": t,
                    }
                elif k == "fin":
                    self.complete = True
                    self.status = r.get("status")
            except (KeyError, TypeError, ValueError):
                self.bad_lines += 1
        for key, snap in hist_last.items():
            try:
                rec.metrics.load_hist(key, snap)
            except (KeyError, TypeError, ValueError):
                self.bad_lines += 1
        self.last_t_s = last_t
        # Spans the run died inside: render them to the last timestamp
        # the file saw, tagged so the Chrome trace shows the death site.
        for r in sorted(open_map.values(), key=lambda x: x.get("t", 0.0)):
            t0 = float(r.get("t", 0.0) or 0.0)
            dur = max(last_t - t0, 0.0)
            attrs = dict(r.get("a") or {})
            attrs["unclosed"] = True
            sp = rec.tracer.add_complete(r.get("name", "?"), t0, dur,
                                         **attrs)
            self.open_spans.append(
                {"name": sp.name, "t_s": t0, "attrs": attrs}
            )

    # -- export surfaces ---------------------------------------------------

    def to_chrome_trace(self) -> dict:
        return self.recorder.tracer.to_chrome_trace()

    def export_chrome_trace(self, path: str) -> str:
        return self.recorder.tracer.export_chrome_trace(path)

    def report(self) -> Dict:
        """A (possibly partial) ``run_report@1`` dict from the file
        alone: phases from the flushed timing records, run gauges,
        resources watermarks, event counts, and the registry dump; the
        extra ``flight`` block says how complete the record is."""
        from .report import build_run_report

        metrics: Dict = {}
        reg = self.recorder.metrics
        for key, tdict in reg.as_dict()["timings"].items():
            if key.startswith("phase."):
                metrics[key[len("phase."):] + "_s"] = tdict["total_s"]
        for key, v in reg.gauges_with_prefix("run.").items():
            metrics[key[len("run."):]] = v
        # Wall-clock absorbed into the registry only on fit completion;
        # for a killed run the last on-disk timestamp is the honest
        # lower bound.
        metrics.setdefault("total_s", round(self.last_t_s, 6))
        hdr = self.header
        rep = build_run_report(
            self.recorder,
            params=hdr.get("params") or {},
            n_points=int(hdr.get("n_points", 0) or 0),
            n_dims=int(hdr.get("n_dims", 0) or 0),
            n_devices=int(hdr.get("n_devices", 1) or 1),
            backend=str(hdr.get("backend", "unknown")),
            metrics=metrics,
        )
        rep["partial"] = not self.complete
        rep["flight"] = {
            "schema": hdr.get("schema", FLIGHT_SCHEMA),
            "path": self.path,
            "records": self.records,
            "bad_lines": self.bad_lines,
            "status": self.status,
            "open_spans": [s["name"] for s in self.open_spans],
            "last_t_s": round(self.last_t_s, 6),
        }
        return rep

    def summary(self) -> str:
        from .report import format_summary

        s = format_summary(self.report())
        if not self.complete:
            inside = ", ".join(s_["name"] for s_ in self.open_spans)
            s += (
                "\n  flight: PARTIAL (run killed"
                + (f" inside {inside}" if inside else "")
                + f"; {self.records} records to t={self.last_t_s:.3f}s)"
            )
        return s


def replay(path: str):
    """Reconstruct a run's observable state from its flight file — the
    post-mortem path for killed runs (``make flight-check``).

    A directory dispatches to :class:`~pypardis_tpu.obs.fleet.FleetReplay`
    over every ``flight-*.jsonl``/``*.jsonl`` member — the multi-process
    post-mortem (one file per host/process)."""
    if os.path.isdir(path):
        from .fleet import FleetReplay

        return FleetReplay(path)
    return FlightReplay(path)
