"""Resource watermarks: host RSS, device live bytes, staging pool.

The failure modes the north-star run actually risks — host OOM during
the streaming Morton sort, HBM exhaustion from staged slab generations,
a staging pool that quietly grows across fits — were invisible: nothing
recorded memory over time, so a killed run said nothing about *why*.
:class:`ResourceSampler` is a lightweight daemon thread (one per fit,
started and ALWAYS joined by ``DBSCAN.train``) that samples

* host RSS (``/proc/self/statm``; ``getrusage`` fallback),
* per-device live bytes (``device.memory_stats()['bytes_in_use']``
  summed over the mesh — 0 on backends that don't report, e.g. the CPU
  CI platform),
* the staging economy's pooled bytes
  (:func:`pypardis_tpu.parallel.staging.pool_nbytes`),

tracking peaks into the fit's registry as ``resources.*`` gauges
(surfaced as ``report()["resources"]`` with guaranteed-finite
watermarks on every route) and streaming raw samples into the flight
file when one is attached — the OOM curve survives the kill.
"""

from __future__ import annotations

import os
import threading
from typing import Optional
from ..utils import envreg

_INTERVAL_DEFAULT_S = 0.2
_THREAD_NAME = "pypardis-resource-sampler"


def host_rss_bytes() -> int:
    """Current resident set size in bytes (0 if unreadable)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 — sampling must never raise
        try:
            import resource

            # ru_maxrss is a PEAK in KB on Linux — a usable fallback
            # watermark even though it never decreases.
            return int(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            ) * 1024
        except Exception:  # noqa: BLE001
            return 0


def device_live_bytes() -> int:
    """Sum of live HBM bytes across devices (0 where unreported)."""
    try:
        import jax

        total = 0
        for d in jax.devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001
                ms = None
            if ms:
                total += int(ms.get("bytes_in_use", 0) or 0)
        return total
    except Exception:  # noqa: BLE001
        return 0


def staging_pool_bytes() -> int:
    """Bytes held by the staging economy (host pool + device cache)."""
    try:
        from ..parallel import staging

        return int(staging.pool_nbytes())
    except Exception:  # noqa: BLE001
        return 0


def rss_soft_limit() -> int:
    """The host-RSS soft watermark in bytes (``PYPARDIS_RSS_SOFT_LIMIT``;
    0 = disabled)."""
    try:
        return int(float(envreg.raw("PYPARDIS_RSS_SOFT_LIMIT", 0)))
    except (TypeError, ValueError):
        return 0


def memory_pressure() -> bool:
    """Whether host RSS currently exceeds the soft limit.

    Evaluated live (one /proc read) so callers outside a sampled fit —
    probes driving ``sharded_dbscan`` directly — see the same verdict.
    The retry/degradation layer consults this to take the host-spill
    merge rung PREEMPTIVELY (``merge='auto'`` resolves to ``'host'``
    under pressure) instead of waiting for the in-graph merge's
    replicated arrays to OOM a watermarked host.
    """
    limit = rss_soft_limit()
    return bool(limit) and host_rss_bytes() > limit


class ResourceSampler:
    """Background watermark sampler for one fit.

    ``start()`` takes an immediate synchronous sample (so even a
    sub-interval fit reports finite watermarks) then spawns the daemon
    thread; ``stop()`` is idempotent, always joins the thread, and
    takes one final sample after the fit's device work settled — the
    no-leaked-threads contract is regression-tested (a fit that raises
    still joins via ``DBSCAN.train``'s finally).
    """

    def __init__(self, recorder, interval_s: Optional[float] = None):
        if interval_s is None:
            interval_s = float(
                envreg.raw(
                    "PYPARDIS_RESOURCE_INTERVAL_S", _INTERVAL_DEFAULT_S
                )
            )
        self._rec = recorder
        self._interval = max(float(interval_s), 0.01)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._peak_host = 0
        self._peak_dev = 0
        self._peak_pool = 0
        self._samples = 0
        self._soft_limit = rss_soft_limit()
        self._pressure_noted = False

    def _sample(self) -> None:
        host = host_rss_bytes()
        dev = device_live_bytes()
        pool = staging_pool_bytes()
        self._samples += 1
        # Watermark -> action hookup: crossing the soft limit emits ONE
        # resource.pressure event per fit (the gauge stays current) and
        # flips the verdict memory_pressure() serves to the retry layer
        # — which then prefers the host-spill merge rung preemptively.
        if self._soft_limit and host > self._soft_limit:
            self._rec.metrics.set("resources.pressure", True)
            if not self._pressure_noted:
                self._pressure_noted = True
                self._rec.event(
                    "resource.pressure", rss_bytes=int(host),
                    soft_limit_bytes=int(self._soft_limit),
                )
        grew = (
            host > self._peak_host or dev > self._peak_dev
            or pool > self._peak_pool
        )
        self._peak_host = max(self._peak_host, host)
        self._peak_dev = max(self._peak_dev, dev)
        self._peak_pool = max(self._peak_pool, pool)
        m = self._rec.metrics
        # Gauges only when a peak moved (each write also lands in the
        # flight file via the registry sink; a flat hour-long run should
        # not cost 18k redundant lines) — plus the first/final samples.
        if grew or self._samples == 1:
            m.set("resources.peak_host_rss_bytes", self._peak_host)
            m.set("resources.peak_device_bytes", self._peak_dev)
            m.set("resources.staging_pool_bytes", self._peak_pool)
        m.set("resources.samples", self._samples)
        fl = getattr(self._rec, "flight", None)
        if fl is not None:
            fl.sample(rss=host, dev=dev, pool=pool)

    def _run(self) -> None:
        while not self._stop_evt.wait(self._interval):
            try:
                self._sample()
            except Exception:  # noqa: BLE001 — never take the fit down
                pass

    def start(self) -> "ResourceSampler":
        try:
            self._sample()
        except Exception:  # noqa: BLE001
            pass
        self._thread = threading.Thread(
            target=self._run, name=_THREAD_NAME, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        try:
            self._sample()
        except Exception:  # noqa: BLE001
            pass
        # Final watermarks are authoritative even if no peak "grew"
        # relative to a stale first sample.
        m = self._rec.metrics
        m.set("resources.peak_host_rss_bytes", self._peak_host)
        m.set("resources.peak_device_bytes", self._peak_dev)
        m.set("resources.staging_pool_bytes", self._peak_pool)
        m.set("resources.samples", self._samples)
