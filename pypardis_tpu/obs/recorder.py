"""The per-run telemetry recorder and the ambient-current mechanism.

One :class:`RunRecorder` per fit bundles the registry, the tracer, and
the event log.  Library layers never take a recorder parameter — they
call :func:`current` (or the module-level :func:`span` / :func:`event`
conveniences), which resolves to the innermost active recorder, or to a
process-wide ambient one when no fit is in flight (so bare calls into
``parallel.sharded`` etc. still record somewhere harmless).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

from .registry import MetricsRegistry, _py
from .trace import Tracer


class RunRecorder:
    """Registry + tracer + event log for one run.

    Events are the discrete occurrences the retry/ladder machinery
    produces — restages, pair-budget overflows, halo-capacity overflows,
    merge-round escalations, first compiles.  Each event appends a
    timestamped dict and bumps the ``events.<kind>`` counter, so the
    report can show counts without replaying the log.
    """

    def __init__(self):
        self.metrics = MetricsRegistry()
        self.tracer = Tracer()
        self.events: List[Dict] = []
        # Optional crash-safe JSONL sink (obs.flight.FlightRecorder);
        # attach_flight wires it into the tracer and the registry.
        self.flight = None

    def attach_flight(self, flight) -> None:
        """Stream this recorder's telemetry into ``flight``: span
        opens/closes, events, gauge/counter writes, and phase timings
        all land in the append-only JSONL file as they happen — the
        durable complement of the in-memory state behind ``report()``.
        """
        flight.set_epoch(self.tracer.epoch_s)
        self.flight = flight
        self.tracer.sink = flight
        self.metrics.sink = flight

    def span(self, name: str, sync: bool = False, **attrs):
        return self.tracer.span(name, sync=sync, **attrs)

    # Event-log retention cap (counters keep exact totals past it):
    # the process-ambient recorder lives forever, so the detail list
    # must not be a slow leak under sustained traffic.
    MAX_EVENTS = 16_384

    def event(self, kind: str, **fields) -> None:
        self.metrics.inc(f"events.{kind}")
        if len(self.events) < self.MAX_EVENTS:
            self.events.append(
                {
                    "kind": kind,
                    "t_s": time.perf_counter() - self.tracer.epoch_s,
                    **{k: _py(v) for k, v in fields.items()},
                }
            )
        if self.flight is not None:
            self.flight.event(kind, fields)

    def event_counts(self) -> Dict[str, int]:
        """{event kind -> count} from the counters."""
        pre = "events."
        return {
            k[len(pre):]: int(v)
            for k, v in self.metrics.counters_with_prefix(pre).items()
        }


# Process-wide fallback: telemetry emitted outside any fit lands here
# instead of being dropped (and instead of every call site null-checking).
_AMBIENT = RunRecorder()
_current: Optional[RunRecorder] = None


def current() -> RunRecorder:
    return _current if _current is not None else _AMBIENT


@contextlib.contextmanager
def use_recorder(rec: RunRecorder):
    """Install ``rec`` as the current recorder for the enclosed block
    (saved/restored, so nested fits each keep their own)."""
    global _current
    prev = _current
    _current = rec
    try:
        yield rec
    finally:
        _current = prev


def span(name: str, sync: bool = False, **attrs):
    """Span on whatever recorder is current."""
    return current().span(name, sync=sync, **attrs)


def event(kind: str, **fields) -> None:
    """Event on whatever recorder is current."""
    current().event(kind, **fields)
