"""The schema'd run report and its one-screen human rendering.

``DBSCAN.report()`` returns :func:`build_run_report`'s dict;
``bench.py`` embeds the same dict in its JSON line, so benchmark rows
and interactive fits expose identical telemetry (the ``BENCH_*.json`` /
``MESHSCALE_*.json`` archives used to reconstruct this by hand from
stderr scrapes).

Schema: ``pypardis_tpu/run_report@1``.  Since the flight-recorder PR
the report always carries a ``resources`` section (peak host RSS /
device live bytes / staging-pool watermarks, finite on every route),
and a report rebuilt by :func:`pypardis_tpu.obs.flight.replay` from an
on-disk flight file (format version ``pypardis_tpu/flight@1``) adds
``partial`` + ``flight`` blocks describing how complete the on-disk
record is.
"""

from __future__ import annotations

from typing import Dict, Optional

from .recorder import RunRecorder
from .registry import _py
from ..utils import envreg

REPORT_SCHEMA = "pypardis_tpu/run_report@1"

# metrics_ keys that describe the shard layout / merge machinery rather
# than timing — they group under report["sharding"].
_SHARDING_KEYS = (
    "halo_factor",
    "pad_waste",
    "owned_cap",
    "halo_cap",
    "n_shard_partitions",
    "n_partitions",
    "merge",
    "merge_rounds",
    "merge_converged",
    "halo_exchange",
    "halo_bytes",
    "input",
    "owner_computes",
    "duplicated_work_factor",
    "staged_bytes_reused",
    "staged_bytes",
    "overlap_efficiency",
    "partition_levels_s",
    "partition_builder",
    # Global-Morton mode (parallel.global_morton): tile-granular
    # boundary exchange + host-stepped pmin fixpoint telemetry.
    "mode",
    "boundary_tiles",
    "boundary_rows",
    "boundary_tile_bytes",
    "boundary_tile_caps",
    "sent_tiles",
    # Sketch-prefiltered send set (ops.sketch): the full-d box-only
    # twins of sent_tiles / boundary_tile_bytes — equal with sketch
    # off, an upper bound (sent_tiles <= sent_tiles_box) with it on.
    "sent_tiles_box",
    "boundary_bytes_box",
    "ring_rounds",
    "fixpoint_rounds",
    # Streaming external sample-sort build (ISSUE 10): spill-bucket
    # geometry of the out-of-core global-Morton sort, plus the chained
    # single-device route's flag.
    "stream_buckets",
    "stream_max_bucket_rows",
    "stream_sample_rows",
    "spill_bytes",
    "chained",
)

# Model-FLOP peak per chip for the MFU denominator, matched by
# substring against jax's device_kind.  Values are the vendor bf16
# matmul peaks — the kernels' default ``precision='high'`` synthesizes
# fp32 from bf16 passes on these units, so MFU against the bf16 peak
# UNDERSTATES utilization by the synthesis factor (~3x); it is a
# consistent, comparable lower bound, not a marketing number.  Override
# with PYPARDIS_PEAK_FLOPS=<flops/sec> for unlisted hardware.
_PEAK_FLOPS_TABLE = (
    ("v5 lite", 197e12),  # v5e ("TPU v5 lite")
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),  # Trillium
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
# No table entry (CPU CI, exotic chips): a nominal 1 TFLOP/s keeps mfu
# finite and comparable across CI runs without pretending to know the
# host's real peak; peak_source says which case applied.
_PEAK_FLOPS_DEFAULT = 1e12


def _peak_flops():
    """(peak_flops, source) for the current default backend's chips."""
    env = envreg.raw("PYPARDIS_PEAK_FLOPS")
    if env:
        return float(env), "env"
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — reporting must never raise
        kind = ""
    for sub, peak in _PEAK_FLOPS_TABLE:
        if sub in kind:
            return peak, f"table:{sub}"
    return _PEAK_FLOPS_DEFAULT, "default"


def _compute_section(
    metrics: Dict, phases: Dict, n_dims: int, precision=None
) -> Dict:
    """Achieved-FLOP/s and MFU from the kernels' in-band pair stats.

    The tiled kernels' work model: every live (row, col) tile pair
    costs ``block^2`` point pairs, each ``2 * (d + 2)`` flops under the
    matmul distance decomposition (the |x|^2+|y|^2-2xy operands carry
    d+2 rows), and the counts/propagation/border passes each walk the
    same live-pair list — so model FLOPs = ``pairs * block^2 * (d+2) *
    2 * passes``.  ``achieved_flops_per_sec`` divides by the cluster
    phase's wall seconds; ``mfu`` divides that by the chip peak.  On
    multi-device meshes ``pairs`` is the worst-case device's total (the
    binding serial path), so the figure is per-chip.  All fields are
    always present and finite — 0.0 means the fit carried no pair
    telemetry (e.g. an empty dataset), never NaN.

    Mixed-precision fields (always present; zero off
    ``precision="mixed"``): band stats are PER-PASS quantities
    measured on the counts pass — classification is deterministic per
    (points, eps, layout), so every pass over the same live pairs
    classifies identically and one measurement covers them all.
    ``precision_mode`` is the canonical mode string; ``band_pairs``
    counts pairs whose fast-pass d^2 landed in the rescore band (pairs
    whose verdict REQUIRED the exact pass); ``band_fraction`` =
    band_pairs / pairs examined per pass (live tile visits x block^2)
    — the <5% acceptance gauge of ROADMAP item 3; ``rescored_pairs``
    = rescored tile visits x block^2 (the extra high-precision FLOPs
    the tile-granular rescore pays per pass) with
    ``rescored_visit_fraction`` its per-visit rate.  MFU is reported
    against BOTH peaks: ``mfu`` keeps its historical
    denominator (the chip's bf16 matmul peak — the single-pass rate
    mixed mode's bulk runs at), and ``mfu_f32_synth`` divides by
    peak/3, the effective ceiling of the bf16_3x f32-synthesizing
    ``high`` mode — the yardstick a mixed-vs-high MFU jump is measured
    against.
    """
    pairs = int(metrics.get("live_pairs", 0) or 0)
    block = int(metrics.get("kernel_block", 0) or 0)
    passes = int(metrics.get("kernel_passes", 0) or 0)
    band_pairs = int(metrics.get("band_pairs", 0) or 0)
    rescored_tiles = int(metrics.get("rescored_tiles", 0) or 0)
    tiles = int(metrics.get("kernel_tiles", 0) or 0)
    try:
        overlap_eff = float(
            metrics.get("exchange_overlap_efficiency", 0.0) or 0.0
        )
    except (TypeError, ValueError):
        overlap_eff = 0.0
    if overlap_eff != overlap_eff or overlap_eff in (
        float("inf"), float("-inf")
    ):
        overlap_eff = 0.0
    cluster_s = float(phases.get("cluster", 0.0) or 0.0)
    flops = float(pairs) * block * block * (n_dims + 2) * 2.0 * passes
    achieved = flops / cluster_s if cluster_s > 0 else 0.0
    peak, source = _peak_flops()
    # Band stats are per-pass (counts-pass measurement), so the
    # fraction denominators are one pass's visits, not passes x pairs.
    visits = float(pairs)
    try:
        from ..ops.precision import norm_precision_mode

        mode = norm_precision_mode(
            "high" if precision is None else precision
        )
    except ValueError:
        mode = str(precision)
    return {
        "live_pairs": pairs,
        "kernel_block": block,
        "kernel_passes": passes,
        # Dispatch-level sparsity gauges (ISSUE 11): the fraction of
        # the dense T^2 tile grid the box-gap extraction kept (the
        # work the compacted dispatch actually visits; < 1.0 on any
        # clustered geometry, == 1.0 when every pair is live), and the
        # share of boundary-ring seconds that ran concurrently with
        # the overlapped owned-prefix counts pass (global-Morton mesh
        # route; 0.0 everywhere else).  Always present and finite.
        "live_pair_fraction": (
            round(min(pairs / float(tiles * tiles), 1.0), 8)
            if tiles > 0 else 0.0
        ),
        "kernel_tiles": tiles,
        "exchange_overlap_efficiency": round(overlap_eff, 6),
        "model_flops": flops,
        "achieved_flops_per_sec": round(achieved, 1),
        "peak_flops": peak,
        "peak_source": source,
        "mfu": round(achieved / peak, 8) if peak > 0 else 0.0,
        "mfu_f32_synth": (
            round(achieved / (peak / 3.0), 8) if peak > 0 else 0.0
        ),
        "precision_mode": mode,
        # Resolved sketch-prefilter width of the fit's kernel passes
        # (0 = off).  With sketch on, band_pairs/band_fraction below
        # count the SKETCH gate's ambiguous pairs (the stats columns
        # are shared with mixed precision — ops.sketch).
        "sketch_k": int(metrics.get("sketch_k", 0) or 0),
        "band_pairs": band_pairs,
        "rescored_pairs": rescored_tiles * block * block,
        "band_fraction": (
            round(band_pairs / (visits * block * block), 8)
            if visits * block > 0 else 0.0
        ),
        "rescored_visit_fraction": (
            round(rescored_tiles / visits, 8) if visits > 0 else 0.0
        ),
    }


def _clean(v):
    if isinstance(v, dict):
        return {k: _clean(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_clean(x) for x in v]
    if getattr(v, "ndim", 0):  # ndarray — scalars fall through to _py
        return _clean(v.tolist())
    v = _py(v)
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)  # callables (metric=...), and anything else exotic


def build_run_report(
    recorder: Optional[RunRecorder],
    *,
    params: Dict,
    n_points: int,
    n_dims: int,
    n_devices: int,
    backend: str,
    metrics: Dict,
    serving: Optional[Dict] = None,
    live: Optional[Dict] = None,
) -> Dict:
    """Assemble the stable report dict from a fit's recorder + metrics.

    ``metrics`` is the model's ``metrics_`` (PhaseTimer ``*_s`` keys +
    the sharded path's stats); the recorder contributes event counts and
    the registry dump.  Every value is a plain Python scalar/list/dict —
    the whole report is json-serializable by construction.
    """
    metrics = {k: _clean(v) for k, v in metrics.items()}

    phases = {
        k[:-2]: round(float(v), 6)
        for k, v in metrics.items()
        if k.endswith("_s") and k != "total_s"
        and isinstance(v, (int, float))
    }

    sharding = {k: metrics[k] for k in _SHARDING_KEYS if k in metrics}
    sharding.setdefault("halo_factor", 0.0)
    sharding.setdefault("pad_waste", 0.0)
    sharding.setdefault("n_partitions", int(metrics.get("n_partitions", 1)))
    # Always-present perf-contract fields (validated by
    # scripts/check_bench_json.py): a single-shard fit clusters each
    # point exactly once (factor 1.0), stages nothing reusable, runs
    # no chained overlap loop (efficiency 0.0), and builds no KD tree
    # (empty per-level timing list).
    sharding.setdefault("duplicated_work_factor", 1.0)
    sharding.setdefault("staged_bytes_reused", 0)
    sharding.setdefault("overlap_efficiency", 0.0)
    sharding.setdefault("partition_levels_s", [])
    # Honest on EVERY route, 1-device chained included: False means the
    # fit really ran the legacy duplicate-and-recluster step (or no
    # sharded step at all), never "unknown" — the comparability contract
    # scripts/check_bench_json.py enforces on all rows.
    sharding.setdefault("owner_computes", False)

    psizes = metrics.get("partition_sizes")
    from ..parallel import dist

    devices: Dict = {
        "count": int(n_devices),
        # Controller processes the fit spanned (1 = classic
        # single-process; >1 = a jax.distributed fleet whose devices
        # this count aggregates).
        "processes": int(dist.process_count()),
    }
    if psizes is not None:
        if n_devices > 0 and len(psizes) % n_devices == 0:
            per_dev = len(psizes) // n_devices
            grouped = [
                psizes[d * per_dev:(d + 1) * per_dev]
                for d in range(n_devices)
            ]
        else:
            grouped = [psizes]
        devices["partition_sizes"] = grouped
        devices["points"] = metrics.get(
            "device_points", [sum(g) for g in grouped]
        )
    else:
        # Single-shard fit: everything on one device.
        devices["partition_sizes"] = [[int(n_points)]]
        devices["points"] = [int(n_points)]

    # Resource watermarks (obs.resources.ResourceSampler gauges):
    # always present, always finite — 0 means the sampler never ran
    # (e.g. an empty fit), never NaN.  scripts/check_bench_json.py
    # enforces the finiteness contract on every bench row.
    res_g = (
        recorder.metrics.gauges_with_prefix("resources.")
        if recorder is not None
        else {}
    )

    def _res(key):
        try:
            v = float(res_g.get(f"resources.{key}", 0) or 0)
        except (TypeError, ValueError):
            return 0
        return int(v) if v == v and abs(v) != float("inf") else 0

    resources = {
        "peak_host_rss_bytes": _res("peak_host_rss_bytes"),
        "peak_device_bytes": _res("peak_device_bytes"),
        "staging_pool_bytes": _res("staging_pool_bytes"),
        "samples": _res("samples"),
    }

    ev = recorder.event_counts() if recorder is not None else {}
    events = {
        "restage": ev.get("retry.restage", 0),
        "transient_retry": sum(
            v for k, v in ev.items() if k.startswith("retry.")
        ),
        "pair_overflow": ev.get("pair_overflow", 0),
        "halo_overflow": ev.get("halo_overflow", 0),
        "merge_unconverged": ev.get("merge_unconverged", 0),
        "compile": ev.get("compile", 0),
        "fault_injected": ev.get("fault_injected", 0),
        "degraded": ev.get("degraded", 0),
    }

    # Fault-tolerance block (always present, schema-enforced): what the
    # fault-injection switchboard fired (utils.faults — 0 on every
    # clean run, by the zero-cost-when-unset contract), how many
    # retries the unified layer spent and abandoned (utils.retry
    # per-site counters summed), and which graceful-degradation rung a
    # terminal failure landed on ("" when none).
    ctr = (
        recorder.metrics.counters_with_prefix("")
        if recorder is not None else {}
    )
    faults_block = {
        "injected": int(ctr.get("faults.injected", 0)),
        "retried": int(sum(
            v for k, v in ctr.items()
            if k.startswith("retry.") and k.endswith(".attempts")
        )),
        "giveups": int(sum(
            v for k, v in ctr.items()
            if k.startswith("retry.") and k.endswith(".giveups")
        )),
        "degraded": int(ctr.get("faults.degraded", 0)),
        "degraded_to": str(
            recorder.metrics.gauge("faults.degraded_to", "")
            if recorder is not None else ""
        ),
    }

    # Host-stepped propagation breakdown (pipeline._cluster_stepped's
    # stepped.* gauges): present only when the fit actually stepped, so
    # "bounded by the tunnel, not compute" reads off prepare/rounds/
    # border/pack seconds and the speculation stats directly.
    stepped = (
        {
            k[len("stepped."):]: v
            for k, v in recorder.metrics.gauges_with_prefix(
                "stepped."
            ).items()
        }
        if recorder is not None
        else {}
    )

    report = {
        "schema": REPORT_SCHEMA,
        "params": _clean(params),
        "run": {
            "n_points": int(n_points),
            "n_dims": int(n_dims),
            "n_devices": int(n_devices),
            "backend": str(backend),
            "total_s": round(float(metrics.get("total_s", 0.0)), 6),
            "points_per_sec": round(
                float(metrics.get("points_per_sec", 0.0)), 1
            ),
        },
        "phases": phases,
        "sharding": sharding,
        "compute": _compute_section(
            metrics, phases, n_dims, precision=params.get("precision")
        ),
        "resources": resources,
        "devices": devices,
        "events": events,
        "faults": faults_block,
        "metrics": (
            recorder.metrics.as_dict()
            if recorder is not None
            else {"counters": {}, "gauges": {}, "timings": {},
                  "hists": {}}
        ),
    }
    if stepped:
        report["stepped"] = stepped
    # Serving-engine gauges (QPS / batch fill / latency percentiles):
    # present only once the model's query engine has answered queries
    # (pypardis_tpu.serve) — scripts/check_bench_json.py validates the
    # block on serve_probe rows.
    if serving:
        report["serving"] = serving
    # Live-update gauges (pypardis_tpu.serve.live): present once the
    # model has a LiveModel attached — insert/delete volumes, the
    # measured re-cluster blast radius (recluster_tile_fraction), the
    # in-place index-refresh economy (epoch + delta bytes), and update
    # latency percentiles.  scripts/check_bench_json.py enforces the
    # block on live_* rows.
    if live:
        report["live"] = live
    # Live-export destinations (obs.export.attach_exporters leaves its
    # gauges in the registry): where the run's metrics could be / still
    # can be scraped.  Absent on runs with no exporter attached.
    export: Dict = {}
    if recorder is not None:
        http_port = recorder.metrics.gauge("metrics.http_port")
        snap_path = recorder.metrics.gauge("metrics.snapshot_path")
        if http_port is not None:
            export["http_port"] = int(http_port)
        if snap_path:
            export["snapshot_path"] = str(snap_path)
    if export:
        report["export"] = export
    return _clean(report)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"


def format_summary(report: Dict) -> str:
    """Render a report as the one-screen run summary."""
    run, sh, ev = report["run"], report["sharding"], report["events"]
    lines = [
        f"pypardis_tpu run — {run['n_points']:,} pts x {run['n_dims']}D "
        f"on {run['n_devices']} {run['backend']} device(s)",
        f"  total {run['total_s']:.3f}s "
        f"({run['points_per_sec']:,.0f} pts/s)",
    ]
    if report["phases"]:
        lines.append(
            "  phases: "
            + " | ".join(
                f"{k} {v:.3f}s" for k, v in sorted(report["phases"].items())
            )
        )
    parts = sh.get("n_shard_partitions", sh.get("n_partitions", 1))
    shard_bits = [
        f"{parts} partition(s)",
        f"halo_factor {sh['halo_factor']:.3f}",
        f"pad_waste {sh['pad_waste']:.3f}",
        f"dup_work {sh['duplicated_work_factor']:.2f}x",
    ]
    if sh.get("input") == "stream":
        bits = f"stream ({sh.get('stream_buckets', '?')} buckets)"
        if sh.get("chained"):
            bits += " chained"
        shard_bits.append(bits)
    if sh.get("mode") == "global_morton":
        shard_bits.append(
            f"boundary {sh.get('boundary_tiles', 0)} tiles "
            f"({_fmt_bytes(sh.get('boundary_tile_bytes', 0))}, "
            f"{sh.get('fixpoint_rounds', 0)} fixpoint round(s))"
        )
        xov = report.get("compute", {}).get(
            "exchange_overlap_efficiency", 0
        )
        if xov:
            shard_bits.append(f"ring {xov:.0%} hidden behind counts")
        # Ring-traffic counters (gm.ring_bytes_sent accumulates the
        # actual bytes every ppermute circulation carried, ladder
        # retries included; gm.ring_tiles_kept the tiles receivers
        # accepted) — previously only trace spans existed, so ring
        # traffic was invisible without exporting a trace.
        ctr = report.get("metrics", {}).get("counters", {})
        sent = ctr.get("gm.ring_bytes_sent", 0)
        if sent:
            shard_bits.append(
                f"ring {_fmt_bytes(sent)} sent / "
                f"{int(ctr.get('gm.ring_tiles_kept', 0))} tiles kept"
            )
    elif "halo_bytes" in sh:
        shard_bits.append(f"halo {_fmt_bytes(sh['halo_bytes'])}")
    if "merge" in sh:
        m = f"merge={sh['merge']}"
        if "merge_rounds" in sh:
            m += f" ({sh['merge_rounds']} rounds)"
        shard_bits.append(m)
    if sh.get("owner_computes"):
        shard_bits.append("owner-computes")
    if sh.get("staged_bytes_reused", 0) > 0:
        shard_bits.append(
            f"staged_reuse {_fmt_bytes(sh['staged_bytes_reused'])}"
        )
    if sh.get("overlap_efficiency", 0) > 0:
        shard_bits.append(f"overlap {sh['overlap_efficiency']:.0%}")
    lines.append("  sharding: " + ", ".join(shard_bits))
    levels = sh.get("partition_levels_s") or []
    if levels:
        lines.append(
            "  partition levels: "
            + " | ".join(f"{t:.3f}s" for t in levels)
            + (f" ({sh.get('partition_builder')})"
               if sh.get("partition_builder") else "")
        )
    st = report.get("stepped")
    if st:
        lines.append(
            "  stepped: "
            f"prepare {st.get('prepare_s', 0):.3f}s | "
            f"rounds {st.get('rounds_s', 0):.3f}s "
            f"({st.get('batches', 0)} x {st.get('batch_size', 0)}"
            f"{', speculative' if st.get('speculate') else ''}) | "
            f"border {st.get('border_s', 0):.3f}s | "
            f"pack {st.get('pack_s', 0):.3f}s"
        )
    comp = report.get("compute", {})
    if comp.get("live_pairs", 0) > 0:
        mixed_bit = ""
        if comp.get("precision_mode") == "mixed":
            mixed_bit = (
                f", mixed: {comp.get('band_fraction', 0):.2%} of pairs "
                f"in-band, "
                f"{comp.get('rescored_visit_fraction', 0):.0%} of tile "
                f"visits rescored"
            )
        frac_bit = ""
        if comp.get("kernel_tiles", 0) > 0:
            frac_bit = (
                f", {comp.get('live_pair_fraction', 0.0):.2%} of tile "
                f"pairs live"
            )
        lines.append(
            f"  compute: {comp['live_pairs']:,} live pairs x "
            f"{comp['kernel_passes']} pass(es) @ block "
            f"{comp['kernel_block']}{frac_bit} -> "
            f"{comp['achieved_flops_per_sec'] / 1e9:,.1f} GFLOP/s "
            f"(mfu {comp['mfu']:.2%} of {comp['peak_flops'] / 1e12:.0f} "
            f"TFLOP/s {comp['peak_source']} peak{mixed_bit})"
        )
    srv = report.get("serving")
    if srv:
        lines.append(
            f"  serving: {srv.get('queries', 0):,} queries in "
            f"{srv.get('batches', 0)} batch(es) @ "
            f"{srv.get('qps', 0):,.0f} q/s, "
            f"p50 {srv.get('p50_ms', 0):.2f}ms "
            f"p99 {srv.get('p99_ms', 0):.2f}ms, "
            f"fill {srv.get('batch_fill', 0):.0%}, "
            f"{srv.get('n_core', 0):,} cores / "
            f"{srv.get('n_leaves', 0)} leaves "
            f"({_fmt_bytes(srv.get('index_bytes', 0))})"
        )
    lv = report.get("live")
    if lv:
        bs = lv.get("batch_sizes") or []
        batch_bit = (
            f", batch mean {sum(bs) / len(bs):.1f} rows "
            f"({lv.get('reclusters_per_write', 0):.3f} reclusters/row)"
            if bs else ""
        )
        compact_bit = (
            f", compact x{lv.get('compactions', 0)} "
            f"({lv.get('compaction_s', 0):.1f}s, "
            f"{lv.get('epoch_swaps', 0)} swap(s))"
            if lv.get("compactions", 0) else ""
        )
        lines.append(
            f"  live: {lv.get('points', 0):,} pts "
            f"({lv.get('cores', 0):,} cores), "
            f"+{lv.get('inserts', 0)}/-{lv.get('deletes', 0)} in "
            f"{lv.get('updates', 0)} update(s), "
            f"recluster x{lv.get('recluster_events', 0)} "
            f"(tile frac {lv.get('recluster_tile_fraction', 0):.2f}), "
            f"epoch {lv.get('index_epoch', 0)} "
            f"({_fmt_bytes(lv.get('index_delta_bytes', 0))} delta), "
            f"insert p50 {lv.get('insert_p50_ms', 0):.1f}ms"
            f"{batch_bit}{compact_bit}"
        )
    tn = report.get("tune")
    if tn:
        plan = tn.get("plan", {})
        cfg = plan.get("config", {})
        pred = tn.get("predicted_phases", {}) or plan.get(
            "predicted", {}
        )
        act = tn.get("actual_phases", {})
        bits = [
            "auto plan " + " ".join(
                f"{k}={cfg.get(k)}"
                for k in ("mode", "block", "precision", "merge",
                          "dispatch")
                if cfg.get(k) is not None
            )
        ]
        if pred.get("total_s") is not None:
            cmp_bit = f"predicted {pred['total_s']:.2f}s"
            if act.get("total_s"):
                cmp_bit += f" vs actual {act['total_s']:.2f}s"
            bits.append(cmp_bit)
        bits.append(
            f"{tn.get('corpus_rows', 0)} corpus row(s), probe "
            f"{tn.get('probe_s', 0.0):.3f}s"
        )
        if plan.get("fallback_reason"):
            bits.append("heuristic fallback")
        lines.append("  tune: " + ", ".join(bits))
    hr = report.get("hierarchy")
    if hr:
        bits = [
            f"{hr.get('mst_edges', 0):,} MST edges in "
            f"{hr.get('boruvka_rounds', 0)} Borůvka round(s) "
            f"(cap {hr.get('round_cap', 0)})",
            f"{hr.get('condensed_clusters', 0)} condensed / "
            f"{hr.get('selected_clusters', 0)} selected cluster(s), "
            f"stability {hr.get('stability_total', 0.0):g}",
            f"eps* {hr.get('eps_selected', 0.0):g} "
            f"(ceiling {hr.get('eps_max', 0.0):g}, "
            f"{hr.get('distance_passes', 1)} distance pass)",
        ]
        if hr.get("ladder"):
            bits.append(f"ladder x{len(hr['ladder'])}")
        lines.append("  hierarchy: " + ", ".join(bits))
    exp = report.get("export")
    if exp:
        dests = []
        if exp.get("http_port") is not None:
            dests.append(f"scrape 127.0.0.1:{exp['http_port']}/metrics")
        if exp.get("snapshot_path"):
            dests.append(f"snapshots {exp['snapshot_path']}")
        hists = report.get("metrics", {}).get("hists") or {}
        hist_bit = ""
        for key in ("serving.latency_ms", *sorted(hists)):
            h = hists.get(key)
            if h and h.get("count"):
                hist_bit = (
                    f"; {key} p50 {h.get('p50_ms', 0):.2f}ms "
                    f"p99 {h.get('p99_ms', 0):.2f}ms "
                    f"({h.get('window_count', 0)} in window)"
                )
                break
        lines.append("  live-metrics: " + ", ".join(dests) + hist_bit)
    res = report.get("resources") or {}
    if res.get("samples", 0) > 0:
        pool = res.get("staging_pool_bytes", 0)
        lines.append(
            f"  resources: host rss peak "
            f"{_fmt_bytes(res.get('peak_host_rss_bytes', 0))}, device "
            f"peak {_fmt_bytes(res.get('peak_device_bytes', 0))}"
            + (f", staging pool {_fmt_bytes(pool)}" if pool else "")
            + f" ({res['samples']} samples)"
        )
    dev_pts = report["devices"].get("points")
    if dev_pts and len(dev_pts) > 1:
        lo, hi = min(dev_pts), max(dev_pts)
        skew = hi / max(lo, 1)
        lines.append(
            f"  devices: {len(dev_pts)} x [{lo:,}..{hi:,}] pts "
            f"(skew {skew:.2f}x)"
        )
    fl = report.get("faults") or {}
    if any(fl.get(k) for k in ("injected", "retried", "giveups",
                               "degraded")):
        bits = (
            f"  faults: {fl.get('injected', 0)} injected, "
            f"{fl.get('retried', 0)} retried, "
            f"{fl.get('giveups', 0)} giveups"
        )
        if fl.get("degraded"):
            bits += f", degraded -> {fl.get('degraded_to', '?')}"
        lines.append(bits)
    lines.append(
        "  events: "
        f"{ev['restage']} restage, {ev['pair_overflow']} pair-overflow, "
        f"{ev['halo_overflow']} halo-overflow, "
        f"{ev['merge_unconverged']} merge-retry, "
        f"{ev['compile']} compile, "
        f"{ev['transient_retry']} transient-retry"
    )
    return "\n".join(lines)
