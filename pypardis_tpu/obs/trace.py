"""Span tracing with device-sync semantics and Chrome-trace export.

Spans nest (a stack per tracer), measure wall time, and — the part
generic tracers get wrong on an async device runtime — can block on the
phase's actual outputs before closing (``sync_on``, lifted from the old
``PhaseTimer``), so the recorded duration includes async-dispatched
device execution rather than just the Python that queued it.

Export is Chrome trace format (the ``traceEvents`` JSON that
chrome://tracing and Perfetto load), complementing the lower-level
``jax.profiler`` trace: this one is the *driver's* view — phases,
ladders, retries — cheap enough to be always on.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Dict, List, Optional


class Span:
    """One completed (or in-flight) span.  ``dur_s`` is None while
    open."""

    __slots__ = ("name", "t0_s", "dur_s", "depth", "attrs", "_pending")

    def __init__(self, name: str, t0_s: float, depth: int, attrs: dict):
        self.name = name
        self.t0_s = t0_s
        self.dur_s: Optional[float] = None
        self.depth = depth
        self.attrs = attrs
        self._pending = None

    def sync_on(self, arrays) -> None:
        """Block on ``arrays`` at span exit so the duration includes the
        device execution that produced them."""
        self._pending = arrays

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)


class Tracer:
    """Collect spans relative to one epoch; export as Chrome trace.

    ``sync=True`` on a span issues a trivial transfer barrier per device
    at exit (TPU executes in order, so that bounds prior compute there);
    prefer ``sync_on`` with the phase's real outputs on out-of-order
    backends — both behaviors are the old ``PhaseTimer``'s, verbatim.
    """

    # Retention cap: the process-ambient recorder lives forever, so an
    # unbounded span list would be a slow leak under sustained traffic.
    # 16k spans ≈ a few MB; beyond it new spans are counted, not kept.
    MAX_SPANS = 16_384

    def __init__(self):
        self.epoch_s = time.perf_counter()
        self.spans: List[Span] = []
        self.dropped = 0
        self._stack: List[Span] = []
        # Optional streaming sink (obs.flight.FlightRecorder): span
        # opens/closes are appended to the JSONL file as they happen.
        self.sink = None
        self._next_id = 0

    @contextlib.contextmanager
    def span(self, name: str, sync: bool = False, **attrs):
        sp = Span(name, time.perf_counter(), len(self._stack), attrs)
        sid = self._next_id
        self._next_id += 1
        self._stack.append(sp)
        if self.sink is not None:
            self.sink.span_open(sid, name, sp.t0_s, sp.depth, attrs)
        failed = False
        try:
            yield sp
        except BaseException:
            failed = True
            raise
        finally:
            self._stack.pop()
            if sp._pending is not None:
                import jax

                jax.block_until_ready(sp._pending)
                sp._pending = None
            elif sync:
                import jax

                # local_devices, not devices: a multi-process fit's
                # global mesh includes devices this controller cannot
                # device_put to.
                for dev in jax.local_devices():
                    # graftlint: disable=device-put-aliasing -- scalar
                    # transfer barrier; no host buffer involved
                    jax.device_put(0, dev).block_until_ready()
            sp.dur_s = time.perf_counter() - sp.t0_s
            self._keep(sp)
            # A span an exception unwinds through stays OPEN in the
            # flight file — the same on-disk signature a SIGKILL
            # leaves, so the last open record marks where the run died
            # (the in-memory span still closes; export_trace on the
            # live model is unaffected).
            if self.sink is not None and not failed:
                self.sink.span_close(sid, name, sp.t0_s, sp.dur_s,
                                     sp.attrs)

    def _keep(self, sp: Span) -> None:
        if len(self.spans) < self.MAX_SPANS:
            self.spans.append(sp)
        else:
            self.dropped += 1

    def add_complete(self, name: str, t0_s: float, dur_s: float,
                     **attrs) -> Span:
        """Record an already-measured interval (absolute perf_counter
        start) — the bridge for timers that measured on their own."""
        sp = Span(name, t0_s, len(self._stack), attrs)
        sp.dur_s = dur_s
        self._keep(sp)
        if self.sink is not None:
            self.sink.span_complete(name, t0_s, dur_s, attrs)
        return sp

    def durations(self) -> Dict[str, float]:
        """{span name -> total seconds} over completed spans."""
        out: Dict[str, float] = {}
        for sp in self.spans:
            if sp.dur_s is not None:
                out[sp.name] = out.get(sp.name, 0.0) + sp.dur_s
        return out

    # -- Chrome trace export ---------------------------------------------

    def to_chrome_trace(self, pid: int = 0,
                        label: str = "pypardis_tpu driver",
                        offset_s: float = 0.0) -> dict:
        """``{"traceEvents": [...]}`` — complete ("X") events in
        microseconds relative to the tracer epoch; loads in
        chrome://tracing and ui.perfetto.dev.

        ``pid``/``label`` name the trace lane (the fleet merge gives
        each host its own); ``offset_s`` shifts every timestamp (fleet
        clock-offset alignment onto the shared timeline).
        """
        events = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": int(pid),
                "tid": 0,
                "args": {"name": str(label)},
            }
        ]
        for sp in self.spans:
            if sp.dur_s is None:
                continue
            events.append(
                {
                    "name": sp.name,
                    "ph": "X",
                    "pid": int(pid),
                    "tid": 0,
                    "ts": (sp.t0_s - self.epoch_s + offset_s) * 1e6,
                    "dur": sp.dur_s * 1e6,
                    "args": {k: _jsonable(v) for k, v in sp.attrs.items()},
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


def _jsonable(v):
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    item = getattr(v, "item", None)
    if callable(item):
        return item()
    return str(v)
