"""Fleet flight aggregation: N per-process flight files, one timeline.

A multi-process run — ``sustained_load`` harness subprocesses today,
``jax.distributed`` pod-scale fits next (ROADMAP item 4) — leaves one
flight JSONL per process/host (``PYPARDIS_FLIGHT=<dir>`` already names
them ``flight-<pid>-<stamp>-<seq>.jsonl``).  Each file's timestamps are
relative to its *own* tracer epoch, so the files cannot be compared
directly: this module aligns them onto one shared timeline and merges.

Alignment: every header record carries ``t_unix``, the wall-clock stamp
written at (relative) t≈0 — the one wall-clock anchor in the stream
(heartbeat/span records are deliberately epoch-relative).  Member ``i``
is shifted by ``offset_i = t_unix_i - min_j t_unix_j``; a member whose
header was lost (killed before the first flush — the same truncation
single-file replay tolerates) gets offset 0 and is flagged.  Heartbeat
records then line up across hosts for free, which is what the monitor
and the merged trace lean on.

Determinism contract (pinned by tests): for a given input set the merge
is **byte-identical** across runs — members are ordered by a stable key
(header wall-clock, then pid, then file name), all serialization uses
sorted keys and fixed separators, and nothing samples a live clock.

Surfaces mirror :class:`~pypardis_tpu.obs.flight.FlightReplay` (which
handles one file): :meth:`to_chrome_trace` (one lane per host),
:meth:`write_merged` (one aligned JSONL), :meth:`report` /
:meth:`summary` (fleet-level partial report).  ``obs.replay(path)``
dispatches here when ``path`` is a directory.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Sequence, Union

from .flight import FlightReplay
from .registry import MetricsRegistry

FLEET_SCHEMA = "pypardis_tpu/fleet_report@1"


def _member_paths(path_or_paths: Union[str, Sequence[str]]) -> List[str]:
    if isinstance(path_or_paths, (list, tuple)):
        return [str(p) for p in path_or_paths]
    root = str(path_or_paths)
    if os.path.isdir(root):
        return sorted(glob.glob(os.path.join(root, "*.jsonl")))
    return [root]


class FleetReplay:
    """N flight files replayed and aligned onto one fleet timeline.

    ``hosts`` holds one descriptor per member, in the merge order that
    also assigns the Chrome-trace lane ``pid``s: ``{host, path, pid,
    t_unix, offset_s, records, bad_lines, complete, status, last_t_s,
    open_spans}``.
    """

    def __init__(self, path: Union[str, Sequence[str]]):
        self.path = path if isinstance(path, str) else None
        paths = _member_paths(path)
        if not paths:
            raise FileNotFoundError(
                f"no flight files under {path!r} (expected *.jsonl)"
            )
        loaded = [(p, FlightReplay(p)) for p in paths]
        # Stable fleet order: wall-clock anchor first (headerless
        # members sort last), then pid, then file name — deterministic
        # for a given input set regardless of directory listing order.
        loaded.sort(
            key=lambda pr: (
                pr[1].header.get("t_unix") is None,
                float(pr[1].header.get("t_unix") or 0.0),
                int(pr[1].header.get("pid") or 0),
                os.path.basename(pr[0]),
            )
        )
        self.members: List[FlightReplay] = [r for _, r in loaded]
        anchors = [
            float(r.header["t_unix"])
            for r in self.members
            if r.header.get("t_unix") is not None
        ]
        t0 = min(anchors) if anchors else 0.0
        self.hosts: List[Dict] = []
        for i, (p, r) in enumerate(loaded):
            t_unix = r.header.get("t_unix")
            off = (float(t_unix) - t0) if t_unix is not None else 0.0
            self.hosts.append(
                {
                    "host": i,
                    "path": p,
                    "pid": r.header.get("pid"),
                    "t_unix": t_unix,
                    "offset_s": round(off, 6),
                    "aligned": t_unix is not None,
                    "records": r.records,
                    "bad_lines": r.bad_lines,
                    "complete": r.complete,
                    "status": r.status,
                    "last_t_s": round(r.last_t_s, 6),
                    "open_spans": [s["name"] for s in r.open_spans],
                }
            )
        self.records = sum(h["records"] for h in self.hosts)
        self.bad_lines = sum(h["bad_lines"] for h in self.hosts)
        self.complete = all(h["complete"] for h in self.hosts)
        self.last_t_s = max(
            (h["offset_s"] + h["last_t_s"] for h in self.hosts),
            default=0.0,
        )
        # Clock-skew sanity: the alignment trusts each member's t_unix
        # anchor, so a fleet whose anchors spread wider than the fit
        # itself plausibly has unsynchronized host clocks — the merged
        # timeline is still deterministic, but cross-host orderings are
        # suspect.  Threshold is the registered knob (seconds).
        from ..utils import envreg

        self.clock_skew_s = round(
            (max(anchors) - min(anchors)) if len(anchors) >= 2 else 0.0,
            6,
        )
        raw = envreg.raw("PYPARDIS_FLEET_SKEW_WARN_S")
        self.skew_warn_s = float(raw) if raw else 5.0
        self.clock_skew_warning = self.clock_skew_s > self.skew_warn_s

    # -- merged surfaces ---------------------------------------------------

    def _lane_label(self, i: int) -> str:
        h = self.hosts[i]
        pid = h["pid"]
        return f"host{i}" + (f" pid={pid}" if pid is not None else "")

    def to_chrome_trace(self) -> dict:
        """One Chrome trace, one lane (``pid``) per host, every event
        shifted onto the shared timeline."""
        meta: List[dict] = []
        xs: List[dict] = []
        for i, member in enumerate(self.members):
            tr = member.recorder.tracer.to_chrome_trace(
                pid=i, label=self._lane_label(i),
                offset_s=self.hosts[i]["offset_s"],
            )
            for ev in tr["traceEvents"]:
                (meta if ev.get("ph") == "M" else xs).append(ev)
        xs.sort(
            key=lambda e: (e.get("ts", 0.0), e.get("pid", 0),
                           str(e.get("name", "")))
        )
        return {"traceEvents": meta + xs, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            f.write(
                json.dumps(self.to_chrome_trace(), sort_keys=True,
                           separators=(",", ":"))
            )
            f.write("\n")
        return path

    def merged_records(self) -> List[Dict]:
        """Every parseable record of every member, stamped with its
        ``host`` index, ``t`` shifted onto the shared timeline, ordered
        by (aligned time, host, original position)."""
        out: List[tuple] = []
        for i, h in enumerate(self.hosts):
            off = h["offset_s"]
            seq = 0
            with open(h["path"], "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        r = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # same tolerance as single-file replay
                    if not isinstance(r, dict):
                        continue
                    t = float(r.get("t", 0.0) or 0.0)
                    r["t"] = round(t + off, 6)
                    r["host"] = i
                    out.append((r["t"], i, seq, r))
                    seq += 1
        out.sort(key=lambda x: x[:3])
        return [r for _, _, _, r in out]

    def write_merged(self, path: str) -> str:
        """The aligned fleet stream as one JSONL file — byte-identical
        for a given input set."""
        with open(path, "w", encoding="utf-8") as f:
            for r in self.merged_records():
                f.write(json.dumps(r, sort_keys=True,
                                   separators=(",", ":")))
                f.write("\n")
        return path

    # -- fleet report ------------------------------------------------------

    def merged_metrics(self) -> MetricsRegistry:
        """All members' registries pooled (counters add, timings and
        histograms merge samples; gauges last-member-wins)."""
        reg = MetricsRegistry()
        for member in self.members:
            reg.merge(member.recorder.metrics)
        return reg

    def heartbeats(self) -> Dict[str, Dict]:
        """Last heartbeat per stage per host, keyed
        ``"<stage>@host<i>"`` on the aligned clock."""
        out: Dict[str, Dict] = {}
        for i, member in enumerate(self.members):
            off = self.hosts[i]["offset_s"]
            for stage, hb in member.heartbeats.items():
                hb = dict(hb)
                hb["t_s"] = round(hb["t_s"] + off, 6)
                hb["host"] = i
                out[f"{stage}@host{i}"] = hb
        return out

    def report(self) -> Dict:
        """Fleet-level partial report: per-host status plus the pooled
        registry — the multi-process analogue of
        :meth:`FlightReplay.report`."""
        reg = self.merged_metrics()
        return {
            "schema": FLEET_SCHEMA,
            "hosts": len(self.hosts),
            "aligned_hosts": sum(1 for h in self.hosts if h["aligned"]),
            "records": self.records,
            "bad_lines": self.bad_lines,
            "complete": self.complete,
            "partial": not self.complete,
            "clock_skew_s": self.clock_skew_s,
            "clock_skew_warning": self.clock_skew_warning,
            "last_t_s": round(self.last_t_s, 6),
            "per_host": self.hosts,
            "heartbeats": self.heartbeats(),
            "registry": reg.as_dict(),
        }

    def summary(self) -> str:
        """One short text block: fleet header + one line per host."""
        rep = self.report()
        lines = [
            "pypardis_tpu fleet: %d hosts, %d records%s, span %.3fs%s"
            % (
                rep["hosts"],
                rep["records"],
                (", %d bad lines" % rep["bad_lines"])
                if rep["bad_lines"] else "",
                rep["last_t_s"],
                "" if rep["complete"] else " — PARTIAL",
            )
        ]
        if rep["clock_skew_warning"]:
            lines.append(
                "  WARNING: member wall-clock anchors spread %.3fs "
                "(> %.1fs) — host clocks look unsynchronized"
                % (rep["clock_skew_s"], self.skew_warn_s)
            )
        for h in self.hosts:
            status = h["status"] or (
                "killed" if not h["complete"] else "?"
            )
            inside = (
                " inside " + ",".join(h["open_spans"])
                if h["open_spans"] else ""
            )
            lines.append(
                "  host%d pid=%s +%.3fs: %d records, %s%s"
                % (h["host"], h["pid"], h["offset_s"], h["records"],
                   status, inside)
            )
        return "\n".join(lines)


def fleet_replay(path: Union[str, Sequence[str]]) -> FleetReplay:
    """Aggregate a directory (or explicit list) of flight files."""
    return FleetReplay(path)
