"""Live metrics export: bounded histograms + scrape/snapshot exporters.

Everything the rest of :mod:`pypardis_tpu.obs` can show is post-hoc:
``report()`` after ``fit()`` returns, a flight file replayed after a
crash.  This module is the *live* plane — the pieces the multi-tenant
gateway and the pod-scale runs need while a fit or load harness is
still in flight:

* :class:`Histogram` — the bounded-bucket latency metric type the
  :class:`~pypardis_tpu.obs.registry.MetricsRegistry` hosts.  Buckets
  are log-spaced milliseconds (8 per decade, 1µs .. 100s, one overflow
  slot), so the structure is O(buckets) forever — sustained serving
  stops accumulating an O(requests) latency list — and percentiles are
  *windowed* (a chunked sliding window, Clipper NSDI'17 treats windowed
  latency tracking as a first-class serving primitive): ``p99`` answers
  "how is serving doing NOW", not "averaged over the whole run".

* :func:`attach_exporters` — the opt-in export plane over one
  :class:`~pypardis_tpu.obs.recorder.RunRecorder`, fed through the same
  sink seam the :class:`~pypardis_tpu.obs.flight.FlightRecorder` uses
  (a :class:`Fanout` tees the tracer/registry/flight sinks, so the
  flight file and the exporters see the identical record stream):

  - :class:`MetricsSnapshotter` — a periodic JSONL snapshot emitter
    (``PYPARDIS_METRICS_SNAPSHOT`` / ``PYPARDIS_METRICS_SNAPSHOT_S``):
    one self-contained JSON line per interval with counters, gauges,
    histogram snapshots, open spans, heartbeats, and resource
    watermarks — each line flushed, so a SIGKILLed run leaves a
    parseable stream (at worst one truncated final line).
  - :class:`MetricsHTTPExporter` — an opt-in stdlib ``http.server``
    scrape endpoint (``PYPARDIS_METRICS_PORT``; ``0`` binds an
    ephemeral port) serving OpenMetrics text exposition at
    ``/metrics``, live while the fit runs:
    ``curl localhost:$PORT/metrics``.

Both exporters are pull-cheap: the write path pays one O(1) histogram
increment per observation; rendering happens on scrape / at the
snapshot interval.  With neither env knob set, :func:`attach_exporters`
is two registry lookups and returns None.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import envreg

HIST_SCHEMA = "pypardis_tpu/hist@1"
SNAPSHOT_SCHEMA = "pypardis_tpu/metrics_snapshot@1"

# Log-spaced millisecond buckets: 8 per decade across 1e-3ms (1µs) ..
# 1e5ms (100s), plus one overflow slot.  65 integer cells — the whole
# point is that this NEVER grows with request count.
_LOG10_LO = -3.0
_PER_DECADE = 8
_DECADES = 8
_NBUCKETS = _PER_DECADE * _DECADES
_EDGES_MS: Tuple[float, ...] = tuple(
    round(10.0 ** (_LOG10_LO + (i + 1) / _PER_DECADE), 9)
    for i in range(_NBUCKETS)
)
_WINDOW_CHUNKS = 8
_WINDOW_DEFAULT_S = 60.0


def _pct_from_counts(counts: List[int], q: float, max_ms: float) -> float:
    """Percentile estimate over one bucket-count vector: find the
    bucket holding the rank, log-interpolate inside it."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = total * (float(q) / 100.0)
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        cum += c
        if cum >= rank:
            if i >= _NBUCKETS:  # overflow bucket: clamp to the max seen
                return round(max(max_ms, _EDGES_MS[-1]), 3)
            hi = _EDGES_MS[i]
            lo = (
                _EDGES_MS[i - 1] if i > 0
                else _EDGES_MS[0] / (10.0 ** (1.0 / _PER_DECADE))
            )
            frac = (rank - (cum - c)) / c
            return round(lo * (hi / lo) ** frac, 3)
    return round(max_ms, 3)


class Histogram:
    """Bounded log-bucket latency histogram with windowed percentiles.

    Lifetime counts live in one fixed vector; the sliding window is a
    ring of ``_WINDOW_CHUNKS`` chunk vectors, each covering
    ``window_s / chunks`` seconds — advancing the ring zeroes expired
    chunks, so the whole structure is a constant ~65 x 9 integer cells
    no matter how many observations land (the memory-bound contract
    ``tests`` pin).  ``percentile()`` answers over the live window and
    falls back to lifetime counts when the window is empty (a just-
    idled server still reports its history instead of zeros).
    """

    __slots__ = (
        "window_s", "_chunk_s", "_life", "_chunks", "_chunk_ids",
        "count", "sum_ms", "max_ms", "_lock",
    )

    def __init__(self, window_s: Optional[float] = None):
        if window_s is None:
            try:
                window_s = float(
                    envreg.raw("PYPARDIS_HIST_WINDOW_S",
                               _WINDOW_DEFAULT_S)
                )
            except ValueError:
                window_s = _WINDOW_DEFAULT_S
        self.window_s = max(float(window_s), 0.5)
        self._chunk_s = self.window_s / _WINDOW_CHUNKS
        self._life = [0] * (_NBUCKETS + 1)
        self._chunks = [
            [0] * (_NBUCKETS + 1) for _ in range(_WINDOW_CHUNKS)
        ]
        self._chunk_ids = [-1] * _WINDOW_CHUNKS
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0
        self._lock = threading.Lock()

    # -- write -------------------------------------------------------------

    def observe(self, value_ms, now_s: Optional[float] = None) -> None:
        ms = float(value_ms)
        if ms != ms:  # NaN never lands in a bucket
            return
        b = bisect.bisect_left(_EDGES_MS, ms)
        cid = int(
            (time.monotonic() if now_s is None else now_s) / self._chunk_s
        )
        slot = cid % _WINDOW_CHUNKS
        with self._lock:
            self._life[b] += 1
            self.count += 1
            self.sum_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms
            if self._chunk_ids[slot] != cid:
                self._chunks[slot] = [0] * (_NBUCKETS + 1)
                self._chunk_ids[slot] = cid
            self._chunks[slot][b] += 1

    def merge_from(self, other: "Histogram") -> "Histogram":
        """Pool ``other``'s lifetime counts into this histogram (fleet
        / registry merges; window state is per-process and not pooled)."""
        with other._lock:
            olife = list(other._life)
            oc, osum, omax = other.count, other.sum_ms, other.max_ms
        with self._lock:
            for i, c in enumerate(olife):
                self._life[i] += c
            self.count += oc
            self.sum_ms += osum
            if omax > self.max_ms:
                self.max_ms = omax
        return self

    def clone(self) -> "Histogram":
        return Histogram(window_s=self.window_s).merge_from(self)

    # -- read --------------------------------------------------------------

    def _window_counts(self, now_s: Optional[float] = None) -> List[int]:
        """Summed counts of the chunks still inside the window.  Caller
        holds the lock."""
        cid = int(
            (time.monotonic() if now_s is None else now_s) / self._chunk_s
        )
        out = [0] * (_NBUCKETS + 1)
        for slot in range(_WINDOW_CHUNKS):
            if cid - _WINDOW_CHUNKS < self._chunk_ids[slot] <= cid:
                ch = self._chunks[slot]
                for i, c in enumerate(ch):
                    if c:
                        out[i] += c
        return out

    @property
    def window_count(self) -> int:
        with self._lock:
            return sum(self._window_counts())

    def percentile(self, q: float, window: bool = True) -> float:
        with self._lock:
            counts = self._window_counts() if window else list(self._life)
            if window and not any(counts):
                counts = list(self._life)
            max_ms = self.max_ms
        return _pct_from_counts(counts, q, max_ms)

    @property
    def nbytes(self) -> int:
        """Fixed structural footprint in cells x 8 — constant by
        construction; the memory-bound regression test pins this."""
        return 8 * (
            len(self._life) + sum(len(c) for c in self._chunks)
        )

    def snapshot(self) -> Dict:
        """One json-serializable dump (``pypardis_tpu/hist@1``):
        windowed p50/p99 plus the nonzero lifetime buckets."""
        with self._lock:
            life = list(self._life)
            wcounts = self._window_counts()
            count, sum_ms, max_ms = self.count, self.sum_ms, self.max_ms
        wtotal = sum(wcounts)
        pct_counts = wcounts if wtotal else life
        return {
            "schema": HIST_SCHEMA,
            "unit": "ms",
            "count": int(count),
            "sum_ms": round(sum_ms, 3),
            "max_ms": round(max_ms, 3),
            "window_s": self.window_s,
            "window_count": int(wtotal),
            "p50_ms": _pct_from_counts(pct_counts, 50, max_ms),
            "p99_ms": _pct_from_counts(pct_counts, 99, max_ms),
            "buckets": [
                [_EDGES_MS[i], int(c)]
                for i, c in enumerate(life[:_NBUCKETS]) if c
            ],
            "overflow": int(life[_NBUCKETS]),
        }

    @classmethod
    def from_snapshot(cls, snap: Dict,
                      window_s: Optional[float] = None) -> "Histogram":
        """Rebuild the lifetime state from a :meth:`snapshot` dict (the
        flight-replay path; window state is not persisted)."""
        h = cls(window_s=window_s or snap.get("window_s"))
        for le, c in snap.get("buckets") or ():
            i = bisect.bisect_left(_EDGES_MS, float(le) * (1 - 1e-9))
            h._life[min(i, _NBUCKETS)] += int(c)
        h._life[_NBUCKETS] += int(snap.get("overflow", 0) or 0)
        h.count = int(snap.get("count", sum(h._life)))
        h.sum_ms = float(snap.get("sum_ms", 0.0))
        h.max_ms = float(snap.get("max_ms", 0.0))
        return h


# ---------------------------------------------------------------------------
# sink plumbing: fan-out + live state
# ---------------------------------------------------------------------------


class Fanout:
    """Tee one sink seam to several sinks.

    The recorder's tracer/registry/flight slots each hold ONE sink
    object; exporters ride the same seam the flight recorder does by
    replacing the slot with a fanout over [previous sink, exporter
    state].  Methods a member lacks are skipped — a sink never has to
    implement the full record-kind surface.
    """

    def __init__(self, sinks):
        self._sinks = [s for s in sinks if s is not None]

    @classmethod
    def of(cls, prev, new) -> "Fanout":
        if isinstance(prev, Fanout):
            return cls(prev._sinks + [new])
        return cls([prev, new])

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        sinks = self._sinks

        def _call(*a, **k):
            for s in sinks:
                fn = getattr(s, name, None)
                if fn is not None:
                    fn(*a, **k)

        return _call


class LiveState:
    """The exporters' in-memory view of the run: open spans, last
    heartbeat per stage, last resource sample, terminal status — the
    record kinds that are *state* rather than aggregates (the registry
    already holds those).  Implements the flight-recorder sink surface
    it needs; everything else no-ops through :class:`Fanout`."""

    def __init__(self, epoch_s: float = 0.0):
        self.epoch_s = float(epoch_s)
        self._lock = threading.Lock()
        self.open_spans: Dict[int, Tuple[str, float, int]] = {}
        self.heartbeats: Dict[str, Dict] = {}
        self.resources: Dict[str, float] = {}
        self.finished: Optional[str] = None
        self.events = 0
        self.last_event: Optional[str] = None
        # Live span-latency histograms, fed on span CLOSE: the registry
        # only learns phase durations when the profiling accumulator
        # observes them (mostly at fit end), but a mid-fit scrape wants
        # latency distributions NOW — the inner rounds (gm ring,
        # fixpoint, stepped batches) close constantly.
        self.hists: Dict[str, Histogram] = {}

    def set_epoch(self, epoch_s: float) -> None:
        self.epoch_s = float(epoch_s)

    def _observe_span(self, name, dur_s) -> None:
        try:
            ms = float(dur_s) * 1e3
        except (TypeError, ValueError):
            return
        key = "span." + str(name)
        h = self.hists.get(key)
        if h is None:
            h = self.hists[key] = Histogram()
        h.observe(ms)

    def span_open(self, sid, name, t0_s, depth, attrs) -> None:
        with self._lock:
            self.open_spans[int(sid)] = (str(name), float(t0_s),
                                         int(depth))

    def span_close(self, sid, name, t0_s, dur_s, attrs) -> None:
        with self._lock:
            self.open_spans.pop(int(sid), None)
            self._observe_span(name, dur_s)

    def span_complete(self, name, t0_s, dur_s, attrs) -> None:
        with self._lock:
            self._observe_span(name, dur_s)

    def event(self, kind, fields) -> None:
        self.events += 1
        self.last_event = str(kind)

    def heartbeat(self, stage, done, total, eta_s) -> None:
        self.heartbeats[str(stage)] = {
            "done": int(done), "total": int(total),
            "eta_s": round(float(eta_s), 3),
        }

    def sample(self, **fields) -> None:
        for k, v in fields.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.resources[str(k)] = float(v)

    def finish(self, status, **fields) -> None:
        self.finished = str(status)

    def spans_now(self) -> List[Tuple[str, float, int]]:
        """Open spans ordered outermost-first, with elapsed seconds."""
        now = time.perf_counter()
        with self._lock:
            items = sorted(self.open_spans.items())
        return [(name, max(now - t0, 0.0), depth)
                for _, (name, t0, depth) in items]

    def hists_snapshot(self) -> Dict[str, Dict]:
        """{span key -> hist@1 snapshot} of the live span histograms."""
        with self._lock:
            return {k: h.snapshot() for k, h in sorted(self.hists.items())}


# ---------------------------------------------------------------------------
# OpenMetrics text exposition
# ---------------------------------------------------------------------------


def _om_name(key: str) -> str:
    return "pypardis_" + str(key).replace(".", "_")


def _om_label(value) -> str:
    s = str(value)
    return (
        s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


# Label-bearing key convention (the multi-tenant gateway's fleet
# telemetry): inside a ``gateway.``-rooted key, a ``model.<id>`` or
# ``tenant.<id>`` segment pair renders as an OpenMetrics LABEL rather
# than a name segment — ``gateway.model.m03.queries`` becomes
# ``pypardis_gateway_queries{model="m03"}`` — so one scrape shows every
# resident model/tenant as series of the same family instead of N
# distinct metric names.
_OM_LABEL_SEGMENTS = ("model", "tenant")


def _om_key_labels(key: str):
    """Split a registry key into (OpenMetrics family name, rendered
    label block) per the convention above; non-gateway keys pass
    through unchanged with an empty label block."""
    parts = str(key).split(".")
    if parts[0] != "gateway":
        return _om_name(key), ""
    kept, labels, i = [], [], 0
    while i < len(parts):
        if parts[i] in _OM_LABEL_SEGMENTS and i + 1 < len(parts) - 1:
            labels.append((parts[i], parts[i + 1]))
            i += 2
        else:
            kept.append(parts[i])
            i += 1
    name = _om_name(".".join(kept))
    if not labels:
        return name, ""
    lab = ",".join(f'{k}="{_om_label(v)}"' for k, v in labels)
    return name, lab


def _om_hist(out: List[str], key: str, snap: Dict) -> None:
    """Append one ``hist@1`` snapshot as an OpenMetrics histogram
    family (cumulative ``_bucket{le=...}`` series + count + sum);
    gateway per-model/per-tenant keys carry their label block on every
    series."""
    n, lab = _om_key_labels(key)
    pre = lab + "," if lab else ""
    suf = "{" + lab + "}" if lab else ""
    out.append(f"# TYPE {n} histogram")
    cum = 0
    for le, c in snap.get("buckets") or ():
        cum += int(c)
        out.append(f'{n}_bucket{{{pre}le="{float(le):g}"}} {cum}')
    cum += int(snap.get("overflow", 0) or 0)
    out.append(f'{n}_bucket{{{pre}le="+Inf"}} {cum}')
    out.append(f"{n}_count{suf} {int(snap.get('count', cum))}")
    out.append(f"{n}_sum{suf} {float(snap.get('sum_ms', 0.0))}")


def render_openmetrics(reg_dump: Dict,
                       state: Optional[LiveState] = None) -> str:
    """The registry dump (+ live state) as OpenMetrics text exposition
    — counters, gauges, timing summaries, histogram bucket series, open
    spans, heartbeats, resource watermarks, terminated by ``# EOF``."""
    out: List[str] = []
    seen_type: set = set()
    for key in sorted(reg_dump.get("counters") or {}):
        v = reg_dump["counters"][key]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        n, lab = _om_key_labels(key)
        if n not in seen_type:
            seen_type.add(n)
            out.append(f"# TYPE {n} counter")
        suf = "{" + lab + "}" if lab else ""
        out.append(f"{n}_total{suf} {v}")
    for key in sorted(reg_dump.get("gauges") or {}):
        v = reg_dump["gauges"][key]
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            continue
        n, lab = _om_key_labels(key)
        if n not in seen_type:
            seen_type.add(n)
            out.append(f"# TYPE {n} gauge")
        suf = "{" + lab + "}" if lab else ""
        out.append(f"{n}{suf} {v}")
    for key in sorted(reg_dump.get("timings") or {}):
        t = reg_dump["timings"][key]
        n = _om_name(key) + "_seconds"
        out.append(f"# TYPE {n} summary")
        out.append(f"{n}_count {int(t.get('count', 0))}")
        out.append(f"{n}_sum {round(float(t.get('total_s', 0.0)), 6)}")
    for key in sorted(reg_dump.get("hists") or {}):
        _om_hist(out, key, reg_dump["hists"][key])
    if state is not None:
        for key, snap in state.hists_snapshot().items():
            _om_hist(out, key, snap)
        spans = state.spans_now()
        if spans:
            out.append("# TYPE pypardis_open_span gauge")
            for name, elapsed, depth in spans:
                out.append(
                    f'pypardis_open_span{{name="{_om_label(name)}",'
                    f'depth="{depth}"}} {round(elapsed, 3)}'
                )
        if state.heartbeats:
            for fam in ("done", "total", "eta_seconds"):
                out.append(f"# TYPE pypardis_heartbeat_{fam} gauge")
            for stage in sorted(state.heartbeats):
                hb = state.heartbeats[stage]
                lab = f'{{stage="{_om_label(stage)}"}}'
                out.append(
                    f"pypardis_heartbeat_done{lab} {hb['done']}"
                )
                out.append(
                    f"pypardis_heartbeat_total{lab} {hb['total']}"
                )
                out.append(
                    f"pypardis_heartbeat_eta_seconds{lab} {hb['eta_s']}"
                )
        for k in sorted(state.resources):
            n = f"pypardis_resource_{_om_label(k)}"
            out.append(f"# TYPE {n} gauge")
            out.append(f"{n} {state.resources[k]}")
        out.append("# TYPE pypardis_run_finished gauge")
        out.append(
            f"pypardis_run_finished {0 if state.finished is None else 1}"
        )
    out.append("# EOF")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


class MetricsSnapshotter:
    """Periodic JSONL metrics-snapshot emitter.

    One self-contained JSON line per interval — counters, gauges,
    histogram snapshots, open spans, heartbeats, resource watermarks —
    appended and flushed line-by-line, so a SIGKILLed process leaves a
    stream where every line but (at worst) the last parses.  The first
    line lands immediately at start; one final line lands at close.
    """

    def __init__(self, recorder, state: LiveState, path: str,
                 interval_s: float = 0.5):
        self._rec = recorder
        self._state = state
        self.path = str(path)
        self.interval_s = max(float(interval_s), 0.05)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="pypardis-metrics-snapshot",
            daemon=True,
        )
        self.lines = 0

    def start(self) -> "MetricsSnapshotter":
        self._emit()
        self._thread.start()
        return self

    def _emit(self) -> None:
        st = self._state
        dump = self._rec.metrics.as_dict()
        line = {
            "schema": SNAPSHOT_SCHEMA,
            "t_unix": round(time.time(), 3),
            "t": round(time.perf_counter() - st.epoch_s, 6),
            "counters": dump["counters"],
            "gauges": {
                k: v for k, v in dump["gauges"].items()
                if isinstance(v, (int, float, str, bool)) or v is None
            },
            "hists": dump.get("hists") or {},
            "span_hists": st.hists_snapshot(),
            "open_spans": [name for name, _, _ in st.spans_now()],
            "heartbeats": st.heartbeats,
            "resources": st.resources,
            "finished": st.finished,
        }
        try:
            payload = json.dumps(line, default=str)
        except (TypeError, ValueError):
            return  # an exporter must never take the run down
        f = self._f
        if f.closed:
            return
        try:
            f.write(payload + "\n")
            f.flush()
            self.lines += 1
        except OSError:
            pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._emit()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            self._emit()
        finally:
            try:
                self._f.close()
            except OSError:
                pass


# The last port an HTTP exporter actually bound in this process —
# discovery hook for in-process harnesses using PYPARDIS_METRICS_PORT=0
# (an ephemeral port the parent could not otherwise learn).
_LAST_HTTP_PORT: List[int] = []


def last_http_port() -> Optional[int]:
    return _LAST_HTTP_PORT[-1] if _LAST_HTTP_PORT else None


class MetricsHTTPExporter:
    """Opt-in OpenMetrics scrape endpoint on stdlib ``http.server``.

    Serves ``GET /metrics`` (OpenMetrics text exposition rendered from
    the live registry + run state) and ``GET /state.json`` (the raw
    snapshot line as JSON) on 127.0.0.1.  ``port=0`` binds an ephemeral
    port (readable from ``.port`` / :func:`last_http_port`).  Requests
    are served from daemon threads; scraping never blocks the fit.
    """

    def __init__(self, recorder, state: LiveState, port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        rec, st = recorder, state

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — stdlib API
                pass

            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path.split("?", 1)[0] == "/metrics":
                    body = render_openmetrics(
                        rec.metrics.as_dict(), st
                    ).encode("utf-8")
                    ctype = (
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8"
                    )
                elif self.path.split("?", 1)[0] == "/state.json":
                    dump = rec.metrics.as_dict()
                    body = json.dumps(
                        {
                            "schema": SNAPSHOT_SCHEMA,
                            "hists": dump.get("hists") or {},
                            "span_hists": st.hists_snapshot(),
                            "gauges": dump["gauges"],
                            "counters": dump["counters"],
                            "open_spans": [
                                n for n, _, _ in st.spans_now()
                            ],
                            "heartbeats": st.heartbeats,
                            "resources": st.resources,
                            "finished": st.finished,
                        },
                        default=str,
                    ).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer(
            ("127.0.0.1", int(port)), _Handler
        )
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        _LAST_HTTP_PORT.append(self.port)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="pypardis-metrics-http", daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)


class ExporterStack:
    """The attached exporters of one recorder, with teardown that
    restores the sink seam exactly as it was."""

    def __init__(self, state: LiveState):
        self.state = state
        self.http: Optional[MetricsHTTPExporter] = None
        self.snapshot: Optional[MetricsSnapshotter] = None
        self._restore: List[Tuple[object, str, object]] = []

    @property
    def http_port(self) -> Optional[int]:
        return self.http.port if self.http is not None else None

    def close(self) -> None:
        if self.snapshot is not None:
            self.snapshot.close()
        if self.http is not None:
            self.http.close()
        for obj, attr, prev in reversed(self._restore):
            setattr(obj, attr, prev)
        self._restore = []


def attach_exporters(recorder, *, port=None, snapshot_path=None,
                     snapshot_interval_s=None) -> Optional[ExporterStack]:
    """Wire the opt-in export plane onto ``recorder`` for the duration
    of a fit / load harness; returns the stack to ``close()``, or None
    when nothing is configured.

    ``port`` defaults to ``PYPARDIS_METRICS_PORT`` (the scrape
    endpoint; ``0`` = ephemeral), ``snapshot_path`` to
    ``PYPARDIS_METRICS_SNAPSHOT``, ``snapshot_interval_s`` to
    ``PYPARDIS_METRICS_SNAPSHOT_S``.  The exporters tee into the same
    sink seam the flight recorder uses (tracer sink, registry sink, and
    the recorder's ``flight`` slot), so heartbeats, spans, and resource
    samples reach them whether or not a flight file is attached.
    Export destinations land in the registry (``metrics.http_port`` /
    ``metrics.snapshot_path``) so ``report()``/``summary()`` can say
    where the live metrics went.
    """
    if recorder is None:
        return None
    if port is None:
        env = envreg.raw("PYPARDIS_METRICS_PORT")
        if env not in (None, ""):
            try:
                port = int(env)
            except ValueError:
                port = None
    if snapshot_path is None:
        snapshot_path = envreg.raw("PYPARDIS_METRICS_SNAPSHOT") or None
    if port is None and snapshot_path is None:
        return None
    if snapshot_interval_s is None:
        try:
            snapshot_interval_s = float(
                envreg.raw("PYPARDIS_METRICS_SNAPSHOT_S", 0.5)
            )
        except ValueError:
            snapshot_interval_s = 0.5

    state = LiveState(epoch_s=recorder.tracer.epoch_s)
    stack = ExporterStack(state)
    for obj, attr in (
        (recorder.tracer, "sink"),
        (recorder.metrics, "sink"),
        (recorder, "flight"),
    ):
        prev = getattr(obj, attr, None)
        stack._restore.append((obj, attr, prev))
        setattr(obj, attr, Fanout.of(prev, state))
    if port is not None:
        try:
            stack.http = MetricsHTTPExporter(recorder, state, port=port)
            recorder.metrics.set("metrics.http_port", stack.http.port)
        except OSError as e:
            import sys

            print(
                f"pypardis_tpu: metrics endpoint bind failed on port "
                f"{port}: {e} — continuing without the scrape endpoint",
                file=sys.stderr,
            )
    if snapshot_path is not None:
        stack.snapshot = MetricsSnapshotter(
            recorder, state, snapshot_path,
            interval_s=snapshot_interval_s,
        ).start()
        recorder.metrics.set(
            "metrics.snapshot_path", str(snapshot_path)
        )
    if stack.http is None and stack.snapshot is None:
        stack.close()  # bind failed and no snapshot: restore the seam
        return None
    return stack
