# Dev shell for pypardis_tpu (parity: reference makefile:10-38, minus the
# docker registry lifecycle — the TPU runtime is provisioned, not built).

PY ?= python

.PHONY: all wheel native test verify tpu-smoke bench bench-smoke demo clean

all: native test

# Reference `make egg` built the Spark-shippable artifact
# (makefile:10-11); the TPU equivalent is a wheel.
wheel:
	$(PY) setup.py bdist_wheel

# Build the native merge library explicitly (it also auto-builds on
# first import of pypardis_tpu._native).
native:
	g++ -O3 -shared -fPIC -o pypardis_tpu/_native/libpypardis_native.so \
		pypardis_tpu/_native/unionfind.cpp

test:
	$(PY) -m pytest tests/ -q -m "not slow"
	$(PY) -m pytest tests/ -q -m slow

# The ROADMAP tier-1 gate, verbatim (scripts/verify.sh): the fast suite
# on the faked 8-device CPU mesh, with the pass-count echo CI scrapes.
verify:
	bash scripts/verify.sh

# Hardware validation: compiles + runs the Pallas kernels through Mosaic
# on the real chip (tests skip themselves off-TPU). Run before shipping
# any kernel change — CPU CI cannot catch lowering breaks.
tpu-smoke:
	PYPARDIS_TEST_PLATFORM=native $(PY) -m pytest tests/test_tpu_smoke.py -q

bench:
	$(PY) bench.py

# Tiny-n benchmark + schema check of the emitted JSON line (the
# metric/value/unit triple plus the run_report@1 telemetry block).
bench-smoke:
	JAX_PLATFORMS=cpu BENCH_N=2000 BENCH_DIM=4 BENCH_REPS=1 \
	BENCH_DEV_REPS=1 $(PY) bench.py | $(PY) scripts/check_bench_json.py

demo:
	$(PY) -m pypardis_tpu.demo

clean:
	rm -rf build dist *.egg-info pypardis_tpu/_native/*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
