# Dev shell for pypardis_tpu (parity: reference makefile:10-38, minus the
# docker registry lifecycle — the TPU runtime is provisioned, not built).

PY ?= python

.PHONY: all wheel native test verify lint tpu-smoke bench bench-smoke \
	partition-probe serve-probe live-probe ingest-probe \
	gateway-probe global-morton-probe fault-probe bench-diff \
	flight-check northstar northstar-smoke streammem-probe \
	sort-probe kernel-probe sweep-probe hierarchy-probe tune-probe \
	sketch-probe monitor monitor-probe multihost-probe demo clean

all: native test

# Reference `make egg` built the Spark-shippable artifact
# (makefile:10-11); the TPU equivalent is a wheel.
wheel:
	$(PY) setup.py bdist_wheel

# Build the native merge library explicitly (it also auto-builds on
# first import of pypardis_tpu._native).
native:
	g++ -O3 -shared -fPIC -o pypardis_tpu/_native/libpypardis_native.so \
		pypardis_tpu/_native/unionfind.cpp

test:
	$(PY) -m pytest tests/ -q -m "not slow"
	$(PY) -m pytest tests/ -q -m slow

# The ROADMAP tier-1 gate, verbatim (scripts/verify.sh): the fast suite
# on the faked 8-device CPU mesh, with the pass-count echo CI scrapes —
# preceded by the sub-second static-invariant gate.
verify: lint
	bash scripts/verify.sh

# graftlint (ISSUE 15): the AST-level invariant checker — tracer-safe
# module constants (R1), device_put aliasing discipline (R2),
# trace-time env reads (R3), the PYPARDIS_* env registry + README
# table sync (R4), seal_f32 FMA discipline (R5), fault-site and
# magic-width hygiene (R6), unused imports (R7).  Stdlib-ast only
# (never imports jax), whole repo in ~3s, zero-entry baseline;
# `--list-rules` / `--envdocs` / `--write-baseline` for the tooling
# surface.  Runtime is itself gated (< 10s) in tests/test_analysis.py
# so this can never become the slow step.
lint:
	$(PY) scripts/graftlint.py

# Hardware validation: compiles + runs the Pallas kernels through Mosaic
# on the real chip (tests skip themselves off-TPU). Run before shipping
# any kernel change — CPU CI cannot catch lowering breaks.
tpu-smoke:
	PYPARDIS_TEST_PLATFORM=native $(PY) -m pytest tests/test_tpu_smoke.py -q

bench:
	$(PY) bench.py

# Tiny-n benchmark + schema check of the emitted JSON line (the
# metric/value/unit triple plus the run_report@1 telemetry block,
# now including the resources watermarks), piped through the
# cross-round regression gate (bench_diff attaches the verdict field;
# check_bench_json --require-diff fails CI on a real regression),
# then the CI-sized partitioner depth-scaling probe (fails when the
# level builder's mp-doubling cost ratio exceeds 1.5x).
bench-smoke: lint partition-probe serve-probe live-probe ingest-probe \
		gateway-probe global-morton-probe fault-probe bench-diff \
		flight-check northstar-smoke kernel-probe sweep-probe \
		hierarchy-probe tune-probe sketch-probe monitor-probe \
		multihost-probe
	JAX_PLATFORMS=cpu BENCH_N=2000 BENCH_DIM=4 BENCH_REPS=1 \
	BENCH_DEV_REPS=1 $(PY) bench.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Dispatch-level sparsity sweep (ISSUE 11): the XLA counts pass under
# dense T^2 dispatch vs the compacted live tile-pair list on the same
# Morton-sorted input — per-mode seconds + the measured
# live_pair_fraction, byte-parity asserted (exits nonzero on
# mismatch).  The dense-dispatch win only appears past a few hundred
# tiles (the scan-iteration overhead the compaction removes); the
# acceptance-scale row is `KP_N=2000000 KP_BLOCK=1024 make
# kernel-probe`.
kernel-probe:
	JAX_PLATFORMS=cpu $(PY) scripts/kernel_probe.py \
	$${KP_N:-40000} $${KP_DIM:-16} $${KP_BLOCK:-256}

# Amortized hyperparameter sweep (ISSUE 13): ONE distance pass at
# eps_max + a cached neighbor-pair graph vs k independent fits on the
# 8-device CPU mesh — gates distance_passes == 1, sweep wall <= 0.5x
# the k solo fits, and per-config byte parity + ARI == 1.0; the
# schema'd sweep@1 row rides the bench_diff cross-round gate.
# Acceptance-scale run: `SWEEP_N=100000 make sweep-probe`.
sweep-probe:
	$(PY) scripts/sweep_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Density-hierarchy probe (ISSUE 18): the eps-free path — mutual-
# reachability MST + stability-condensed tree over the cached pair
# graph — timing an 8-rung sweep(X, "auto") ladder against 8 solo
# fits at the same eps values.  Gates: distance_passes == 1 for the
# whole ladder, ladder wall <= 0.2x the solo sum (amortization >= 5),
# per-rung byte parity + ARI == 1.0, boruvka_rounds <= round_cap, and
# mst_edges == n_live - n_components; the schema'd hierarchy@1 row
# rides the bench_diff cross-round gate.  Acceptance-scale run:
# `HIER_N=100000 make hierarchy-probe`.
hierarchy-probe:
	$(PY) scripts/hierarchy_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Auto-tuning probe (ISSUE 14): one measured DBSCAN(auto=True) fit —
# probe + corpus harvest + plan — against a >= 6-point explicit
# config lattice on the same geometry.  Gates: planned config's wall
# within 1.25x the best lattice point, probe+plan overhead <= 5% of
# the auto fit's wall, auto labels byte-identical to the same
# explicit config, finite predicted phases; the schema'd tune@1 row
# rides the bench_diff cross-round gate.  Acceptance-scale run:
# `TUNE_N=1000000 make tune-probe`.
tune-probe:
	$(PY) scripts/tune_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Sketch-prefilter probe (ISSUE 17): the random-projection certified
# gate at d in {64, 512} — counts-pass wall sketch ON vs OFF with
# byte parity per dim, six full fits (fused / KD / global_morton x
# sketch auto/off) byte-compared, and the GM boundary-bytes invariant
# (the sketch send gate can only shrink the ring).  Headline win gated
# at SKETCH_MIN_WIN (1.25 on the CPU mesh); the schema'd sketch@1 row
# rides the bench_diff cross-round gate.  Acceptance-scale run on TPU:
# `SKETCH_N=65536 SKETCH_MIN_WIN=3 make sketch-probe`.
sketch-probe:
	$(PY) scripts/sketch_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Cross-round bench regression gate on the committed archives: the
# r4->r5 4.7% delta must come back as the PR 2 manual diagnosis did —
# 'noise' (overlapping raw sample ranges) — and a real regression
# (disjoint ranges, >5% best-of-N slowdown) exits nonzero.  The
# --expect pin makes the reproduced verdict itself a CI invariant.
bench-diff:
	$(PY) scripts/bench_diff.py --prior BENCH_r04.json \
	--current BENCH_r05.json --expect noise

# Fault-tolerance probe (ISSUE 9): injects a mid-fixpoint shard
# failure, a staging OOM, and a serving hang (PYPARDIS_FAULTS sites),
# asserts labels byte-identical to the clean run through the unified
# retry/degradation ladder, SIGKILLs a checkpointing child fit and
# proves train(resume=) kill/resume byte-parity, then schema-checks the
# emitted row (check_bench_json enforces the faults block: clean rows
# must be all-zero, fault rows carry the real injected/retried counts).
fault-probe:
	FAULT_N=$${FAULT_N:-3000} $(PY) scripts/fault_probe.py \
	| $(PY) scripts/check_bench_json.py

# North-star run (ISSUE 10 / ROADMAP item 1): chunked blob generation
# straight to a disk memmap, streaming global-Morton build (external
# sample-sort), chained (1-device) or distributed (mesh) execute, host
# merge, PYPARDIS_CKPT resume on — one schema'd northstar@1 row
# decomposing build/exchange/compute/merge seconds + peak RssAnon.
# Defaults: 100M x 16-D on TPU hardware; 2M (the largest CPU-feasible
# smoke) elsewhere.  Override: `NS_N=100000000 make northstar`.
# The emitted row pipes through the same cross-round range gate BENCH
# rows get: bench_diff finds the latest committed NORTHSTAR_*.json at
# the SAME geometry (n/dim/devices/mode), attaches the verdict, and
# check_bench_json --require-diff fails CI on a regression verdict.
northstar:
	$(PY) scripts/northstar_run.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# CI-sized northstar composition (wired into bench-smoke): the same
# full driver at 120k proves the plumbing + row schema on every PR.
northstar-smoke:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	NS_N=$${NS_N:-120000} NS_DIM=$${NS_DIM:-16} \
	$(PY) scripts/northstar_run.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Streaming-build memory probe (ISSUE 10 acceptance gauge): peak host
# ANON memory of the external sample-sort + per-shard assembly vs the
# in-RAM morton_range_split build, on a disk-backed memmap.  The
# acceptance geometry: `STREAMMEM_N=10000000 make streammem-probe`
# (gate: stream build anon < 0.25x dataset bytes; exits nonzero past
# it).
streammem-probe:
	$(PY) scripts/streammem_probe.py $${STREAMMEM_N:-2000000} \
	$${STREAMMEM_DIM:-16} $${STREAMMEM_EPS:-2.4} \
	$${STREAMMEM_MODE:-gm_stream}

# Device sort/morton/gather primitive costs + (--stream) the host
# external sample-sort vs in-RAM morton_range_split at the same N.
sort-probe:
	$(PY) scripts/sort_probe.py $${SORT_N:-1000000} \
	$${SORT_DIM:-16} --stream

# Crash-safety smoke: fit with the flight recorder enabled, SIGKILL it
# mid-run, then reconstruct a Chrome trace + partial report from the
# on-disk JSONL alone (scripts/flight_check.py).  FLIGHT_N sizes the
# child fit.
flight-check:
	FLIGHT_N=$${FLIGHT_N:-40000} $(PY) scripts/flight_check.py

# Zero-duplication global-Morton mode probe (ISSUE 5): runs the same
# geometry through the owner-computes KD mode and mode="global_morton"
# (labels must byte-match; manifold row pins ARI vs the fused engine),
# then schema-checks the emitted row — a silent fallback to the KD halo
# path (halo_exchange != morton_ring, dup factor != 1.0, or boundary
# bytes >= legacy halo bytes) fails CI.  Acceptance-scale run:
# `GM_N=200000 make global-morton-probe`.
global-morton-probe:
	GM_N=$${GM_N:-20000} GM_DIM=$${GM_DIM:-16} \
	$(PY) scripts/global_morton_probe.py \
	| $(PY) scripts/check_bench_json.py

# Serving probe: per-batch-size QPS + p50/p99 rows from the query
# engine, each checked against the brute-force core-point oracle; the
# emitted telemetry (run_report@1 + its new `serving` block) is
# schema-validated like the bench row.
serve-probe:
	JAX_PLATFORMS=cpu SERVE_N=$${SERVE_N:-4000} \
	SERVE_Q=$${SERVE_Q:-1024} $(PY) scripts/serve_probe.py \
	| $(PY) scripts/check_bench_json.py

# Live-update probe (ISSUE 8): insert/delete latency p50/p99 + the
# measured re-cluster blast radius (asserts recluster_tile_fraction <
# 1.0 for a boundary-interior insert, incremental ARI == 1.0 vs full
# refit, predict bitwise oracle-exact on the updated index), a
# Poisson sustained-load row with >= 4 concurrent clients, and the
# replicated-index throughput row (>= 2x gate enforced on hosts with
# parallel device execution; the 1-core CI container reports the ratio
# and asserts bitwise parity).  Schema'd like every bench row.
live-probe:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	LIVE_N=$${LIVE_N:-4000} LIVE_SECONDS=$${LIVE_SECONDS:-1.5} \
	$(PY) scripts/live_probe.py \
	| $(PY) scripts/check_bench_json.py

# Streaming-ingest probe (ISSUE 12): asserts one-recluster-dispatch +
# one-index-delta per insert_batch (B=256, vs the per-point control),
# IngestQueue coalescing with ARI == 1.0 vs full refit, predict
# bitwise oracle-exact across a background-compaction epoch swap
# (in-flight tickets resolve against the old generation, zero
# dropped), then runs the mixed reader+writer Poisson harness across
# >= 1 compaction and emits the schema'd ingest@1 row through the
# bench_diff cross-round gate.
ingest-probe:
	JAX_PLATFORMS=cpu \
	INGEST_N=$${INGEST_N:-4000} INGEST_SECONDS=$${INGEST_SECONDS:-2.0} \
	$(PY) scripts/ingest_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Multi-tenant gateway probe (ISSUE 19): >= 8 registered models under
# a device-slab byte budget that forces LRU eviction, readmitted
# predictions byte-identical to pre-eviction, per-tenant quota
# shedding isolated, then Zipf-distributed multi-tenant traffic
# across >= 1 mid-run hot-swap epoch swap with zero dropped tickets —
# emitted as the schema'd gateway@1 row through the bench_diff
# cross-round gate.
gateway-probe:
	JAX_PLATFORMS=cpu \
	GATEWAY_MODELS=$${GATEWAY_MODELS:-10} \
	GATEWAY_SECONDS=$${GATEWAY_SECONDS:-2.0} \
	$(PY) scripts/gateway_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Live run monitor (ISSUE 16): tail a flight file or a directory of
# them (phase stack, heartbeat ETAs, resource watermarks, latency
# histogram percentiles).  `make monitor MONITOR_PATH=/path/to/flight`
# — add MONITOR_ARGS="--once --json" etc. for scripting.
monitor:
	@test -n "$(MONITOR_PATH)" || \
	{ echo "usage: make monitor MONITOR_PATH=<flight .jsonl or dir>"; \
	exit 2; }
	$(PY) scripts/monitor.py $(MONITOR_PATH) $(MONITOR_ARGS)

# Live-observability probe (ISSUE 16): fits with the scrape endpoint +
# snapshot stream enabled and, mid-fit, scrapes /metrics until one
# OpenMetrics response carries an open span, heartbeat progress, AND a
# latency-histogram series at once; then the serving histogram over a
# fresh endpoint, the snapshot stream, and a scripts/monitor.py render
# — one schema'd monitor@1 row through the bench_diff cross-round
# gate.  MONITOR_N sizes the fit (doubles on its own when the fit
# outruns the scraper).
monitor-probe:
	MONITOR_N=$${MONITOR_N:-40000} $(PY) scripts/monitor_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# Pod-scale execution probe (ISSUE 20): a localhost jax.distributed
# fleet (2 processes x 4 faked CPU devices = the reference 8-device
# mesh) — fit parity byte-identical to the single-process run under
# both merges + the KD route, the shared-store streaming build's
# pass 2/3 partition across processes (byte-identical; the >= 1.8x
# P=4 speedup gate applies only on hosts with >= 4 cores), a SIGKILL-
# mid-fixpoint drill resumed from the coordinator's jobstate snapshot
# back to byte parity, and the per-process flight files merged by
# obs.replay with the clock-skew flag quiet — one schema'd
# multihost@1 row through the bench_diff cross-round gate.
multihost-probe:
	MH_N=$${MH_N:-3000} $(PY) scripts/multihost_probe.py \
	| $(PY) scripts/bench_diff.py --annotate --baseline-dir . \
	| $(PY) scripts/check_bench_json.py --require-diff

# KDPartitioner build-time-vs-max_partitions rows (both builders, with
# per-level breakdowns).  Full-size run: `PROBE_N=10000000 make
# partition-probe`.
partition-probe:
	PROBE_N=$${PROBE_N:-200000} PROBE_MPS=$${PROBE_MPS:-8,16} \
	PROBE_REPS=$${PROBE_REPS:-3} $(PY) scripts/partition_probe.py

demo:
	$(PY) -m pypardis_tpu.demo

clean:
	rm -rf build dist *.egg-info pypardis_tpu/_native/*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
