"""Packaging for pypardis_tpu (parity: reference setup.py:6-9).

The reference ships a plain setuptools package plus a Spark-submittable
egg (reference makefile:10-11).  The TPU framework ships a wheel; the
native merge library is compiled lazily at import by
``pypardis_tpu._native`` (ctypes + g++), so the wheel stays pure-Python
and portable across hosts with a toolchain.
"""

from setuptools import find_packages, setup

setup(
    name="pypardis_tpu",
    version="0.1.0",
    description=(
        "TPU-native distributed density-based clustering (DBSCAN) on "
        "JAX/XLA/Pallas — the capabilities of pyParDis, redesigned for "
        "TPU meshes"
    ),
    packages=find_packages(include=["pypardis_tpu", "pypardis_tpu.*"]),
    package_data={"pypardis_tpu._native": ["*.cpp"]},
    python_requires=">=3.10",
    install_requires=["numpy", "jax"],
    extras_require={
        "test": ["pytest", "scikit-learn", "scipy"],
        "plot": ["matplotlib"],
    },
)
