"""Benchmark: points/sec/chip on the BASELINE.json scale-up config.

Runs the 16-D make_blobs scale-up benchmark (BASELINE.json config 2,
shrunk to what one chip holds comfortably) through the public DBSCAN API
on the real device, times steady-state (post-compile), and prints ONE
JSON line.  ``vs_baseline``: the reference publishes no numbers
(BASELINE.md — ``published: {}``), so the comparison is against a
single-node sklearn DBSCAN run on the same data/host, the reference's
own per-partition engine and correctness oracle.

Every row carries its oracle (round-4 review, Missing #2):
``ari_vs_truth`` scores the labels against the generator's assignment,
and at bench size a FULL sklearn fit on the same data adds
``ari_vs_sklearn`` — the reference's only published correctness
baseline (/root/reference/README.md:42).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from benchdata import (  # noqa: E402
    ari_vs_truth, make_blob_data, make_embedding_data,
)


def main():
    n = int(os.environ.get("BENCH_N", 200_000))
    dim = int(os.environ.get("BENCH_DIM", 16))
    skew = os.environ.get("BENCH_SKEW") or None
    # BENCH_GEOM=embedding swaps the isotropic blobs for the low-rank
    # + full-rank-noise embedding geometry (benchdata.
    # make_embedding_data) — the BENCH_DIM axis rows at d in {64, 256,
    # 1024} that the sketch prefilter targets.  eps=2.0 sits between
    # the latent intra-cluster spread (~std*sqrt(2*latent_dim) ~ 1.4)
    # and the thinned 8*std center separation at every benched dim.
    geom = os.environ.get("BENCH_GEOM", "blob")
    if geom == "embedding":
        eps, min_samples = 2.0, 10
        X, truth = make_embedding_data(n, dim)
    else:
        # 16-D gaussian blobs with sigma=0.4: typical intra-cluster
        # pair distance is ~sigma*sqrt(2*dim) ~ 2.26, so eps=2.4
        # recovers blobs.
        eps, min_samples = 2.4, 10
        X, truth = make_blob_data(n, dim, n_centers=32, std=0.4,
                                  skew=skew)

    from pypardis_tpu import DBSCAN

    import jax

    n_chips = jax.device_count()
    # Kernel precision under test (BENCH_PRECISION=mixed benches the
    # banded fast-pass mode; labels are byte-identical to high by
    # contract, so rows stay comparable across modes).
    precision = os.environ.get("BENCH_PRECISION", "high")

    def run(data):
        model = DBSCAN(
            eps=eps, min_samples=min_samples, block=2048,
            precision=precision,
        )
        labels = model.fit_predict(data)
        return labels, model

    run(X)  # compile warm-up (host path)
    # Host end-to-end: includes the host->device transfer, whose
    # throughput on this tunneled deployment swings ~10x with ambient
    # load — reported as a secondary number, best-of-N like the
    # primary (a single sample previously made BENCH and BENCH_SCALE
    # disagree by 2x on the same config purely from link noise).
    reps = int(os.environ.get("BENCH_REPS", 3))
    host_samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        labels, _model = run(X)
        host_samples.append(time.perf_counter() - t0)
    host_dt = min(host_samples)

    # Primary metric: fits on device-resident data — the TPU analogue
    # of the reference's train() on an already-distributed RDD (the
    # RDD's load/parallelize cost is outside its timings too).  Results
    # still come back to the host inside the timed region.  Best-of-N:
    # the tunnel's per-transfer latency noise lands in every run; the
    # minimum is the reproducible steady state.
    Xd = jax.device_put(X)
    run(Xd)  # device-path warm-up
    dev_reps = int(os.environ.get("BENCH_DEV_REPS", max(5, reps)))
    samples = []
    band_stats = []
    for _ in range(dev_reps):
        t0 = time.perf_counter()
        labels, model = run(Xd)
        samples.append(time.perf_counter() - t0)
        # Per-rep band stats (zeros off precision=mixed): archived raw
        # like samples_s, so cross-round bench_diff verdicts on a mixed
        # row can tell a band-fraction drift (data/layout change) from
        # timing noise without rerunning.
        band_stats.append([
            int(model.metrics_.get("band_pairs", 0) or 0),
            int(model.metrics_.get("rescored_tiles", 0) or 0),
        ])
    dt = min(samples)
    pts_per_sec_chip = n / dt / n_chips

    # Flight-recorder overhead on the same warm device-path geometry
    # (ISSUE 6 acceptance: <= 2% at the CI geometry, measured and
    # stated in the row).  Best-of-2 with the JSONL sink on, against
    # the best-of-N baseline above; BENCH_FLIGHT=0 skips.
    flight_overhead = None
    if os.environ.get("BENCH_FLIGHT", "1") != "0":
        import tempfile

        fdir = tempfile.mkdtemp(prefix="bench_flight_")
        fl_samples = []
        for i in range(2):
            fpath = os.path.join(fdir, f"rep{i}.jsonl")
            t0 = time.perf_counter()
            DBSCAN(
                eps=eps, min_samples=min_samples, block=2048, flight=fpath
            ).fit_predict(Xd)
            fl_samples.append(time.perf_counter() - t0)
        flight_overhead = round(min(fl_samples) / dt - 1.0, 4)

    ari_truth = ari_vs_truth(labels, truth)

    # sklearn single-node baseline on the same data (subsampled if huge,
    # scaled linearly — sklearn is the reference's compute engine).
    from sklearn.cluster import DBSCAN as SKDBSCAN

    sk_n = min(n, 50_000)
    t0 = time.perf_counter()
    SKDBSCAN(eps=eps, min_samples=min_samples).fit(X[:sk_n])
    sk_dt = time.perf_counter() - t0
    sk_pts_per_sec = sk_n / sk_dt

    # Full-data sklearn ORACLE (not timing): ari_vs_sklearn at bench
    # size.  Gated on n (sklearn's neighborhood lists are O(n * cluster
    # size) memory) and skippable via BENCH_SK_ORACLE=0.
    ari_sklearn = None
    if os.environ.get("BENCH_SK_ORACLE", "1") != "0" and n <= 200_000:
        sk_full = SKDBSCAN(eps=eps, min_samples=min_samples).fit(X).labels_
        from sklearn.metrics import adjusted_rand_score

        ari_sklearn = round(float(adjusted_rand_score(sk_full, labels)), 4)

    print(
        json.dumps(
            {
                "metric": f"points_per_sec_per_chip_dbscan_{dim}d_{n}pts"
                + ("_embed" if geom == "embedding" else "")
                + (f"_{skew}" if skew else ""),
                # The BENCH_DIM axis as first-class row fields (the
                # d in {64, 256, 1024} sketch-prefilter sweep groups
                # on these instead of parsing the metric name).
                "dim": dim,
                "geometry": geom,
                "value": round(pts_per_sec_chip, 1),
                "unit": "points/sec/chip",
                "vs_baseline": round(pts_per_sec_chip / sk_pts_per_sec, 3),
                "host_e2e_value": round(n / host_dt / n_chips, 1),
                # Run-to-run spread of the device samples: the tunneled
                # chip's ambient load swings timings; when BENCH and
                # BENCH_SCALE disagree on the same config, this says
                # whether the delta is noise (large spread) or real.
                "device_sample_spread": round(max(samples) / min(samples), 2),
                # Raw per-rep wall times (device path, then host e2e):
                # archived so a cross-round delta in the best-of-N
                # headline is attributable to link/ambient noise vs a
                # real regression WITHOUT rerunning (the r4->r5 4.7%
                # question was undiagnosable from the archives alone).
                "samples_s": [round(s, 4) for s in samples],
                "host_samples_s": [round(s, 4) for s in host_samples],
                # Kernel precision mode of this row and the raw
                # per-rep [band_pairs, rescored_tiles] (all-zero off
                # precision=mixed) — the mixed-mode analogue of the
                # raw samples archive.
                "precision_mode": precision,
                "band_stats": band_stats,
                # Relative cost of the always-flushing JSONL flight
                # sink on this geometry (best-of-2 vs the best-of-N
                # baseline; the ISSUE 6 acceptance bound is <= 2% at
                # the 200k x 16-D CI geometry).  Negative values mean
                # the delta drowned in run-to-run noise.
                "flight_overhead": flight_overhead,
                "ari_vs_truth": round(ari_truth, 4),
                "ari_vs_sklearn": ari_sklearn,
                # The same run_report@1 schema DBSCAN.report() returns:
                # phase times, per-device partition sizes, halo/pad
                # overheads, and ladder event counts ride with every
                # row (the BENCH_*/MESHSCALE_* archives used to
                # reconstruct these by hand from stderr).  Telemetry of
                # the LAST warm device-path rep — representative of the
                # steady state the primary metric reports.
                "telemetry": model.report(),
            }
        )
    )
    # Sanity line on stderr only — stdout stays a single JSON line.
    print(
        f"clusters={labels.max() + 1} noise={(labels == -1).sum()} "
        f"t={dt:.2f}s samples={[round(s, 2) for s in samples]} "
        f"host_t={host_dt:.2f}s sklearn@{sk_n}={sk_dt:.2f}s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
