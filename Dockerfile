# Runnable-environment parity with the reference's container story
# (/root/reference/Dockerfile:1-23 — ubuntu + python + requirements).
# TPU equivalent: the official JAX CPU image runs the full test suite on
# a virtual 8-device mesh; on TPU VMs, swap the base for a libtpu image
# (e.g. the Cloud TPU JAX release) — the code paths are identical.
FROM python:3.11-slim

WORKDIR /opt/pypardis_tpu

# Native toolchain for the C++ union-find resolver (built lazily at
# import; the wheel works without it via the numpy fallback).
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ make \
    && rm -rf /var/lib/apt/lists/*

COPY setup.py makefile ./
COPY pypardis_tpu ./pypardis_tpu
COPY tests ./tests

RUN pip install --no-cache-dir \
    "jax[cpu]" numpy scipy scikit-learn pytest \
    && pip install --no-cache-dir -e .

# The test harness fakes an 8-device mesh on CPU (tests/conftest.py), so
# the distributed path is exercised without TPU hardware.
CMD ["python", "-m", "pytest", "tests/", "-q"]
