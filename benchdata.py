"""Shared benchmark data generation WITH ground truth.

Round-4 review, Missing #2: every scale artifact validated by expected
cluster count and cross-mode label SHAs only — the generator's
assignment was computed and thrown away.  This module is the single
generator for ``bench.py`` and every ``scripts/*_probe.py``: it returns
``(X, truth)`` so each artifact row can carry ``ari_vs_truth`` (free at
any N), and it owns the SKEWED variant (round-4 Missing #3: log-normal
cluster populations spanning ~100x with mixed stds — an honest
stand-in for the GeoLife/KDD density skew of BASELINE configs 3/5,
which uniform constant-density blobs never exercised).
"""

from __future__ import annotations

import gzip
import hashlib
import os

import numpy as np

_CHUNK = 1 << 20

# Real-dataset fixture (ISSUE 14 satellite): the UCI Optical
# Recognition of Handwritten Digits corpus — REAL measured data (8x8
# grayscale counts, 64-D), the classic embedding-shaped workload — as
# redistributed by scikit-learn.  The download URL is pinned to the
# sklearn tag whose file the checksum below was computed from; the
# committed offline fallback (data/uci_optdigits_subsample.npz) holds
# the same 1797 real rows, so tier-1 CI never needs the network.
_REAL_DATASET_URL = (
    "https://raw.githubusercontent.com/scikit-learn/scikit-learn/"
    "1.7.2/sklearn/datasets/data/digits.csv.gz"
)
_REAL_DATASET_SHA256 = (
    "09f66e6debdee2cd2b5ae59e0d6abbb73fc2b0e0185d2e1957e9ebb51e23aa22"
)
_REAL_DATASET_FILE = "digits.csv.gz"
_SUBSAMPLE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "data",
    "uci_optdigits_subsample.npz",
)


def _real_data_dir() -> str:
    return os.environ.get("PYPARDIS_DATA_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "pypardis_tpu", "data"
    )


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _parse_digits_csv(path: str):
    with gzip.open(path, "rb") as f:
        raw = np.loadtxt(f, delimiter=",")
    return raw[:, :-1].astype(np.float64), raw[:, -1].astype(np.int32)


def load_real_dataset(data_dir: str | None = None, *,
                      download: bool = True):
    """The real-dataset fixture: ``(X, y, meta)`` — UCI optdigits.

    Resolution order: (1) a checksum-verified cached copy under
    ``data_dir`` (default ``PYPARDIS_DATA_DIR`` or
    ``~/.cache/pypardis_tpu/data``); (2) a fresh download (verified
    against the pinned sha256, then cached); (3) offline/any-failure
    fallback to the COMMITTED subsample of the same real rows —
    ``meta["offline"]`` says which path served, and tests stay green
    with no network (the graceful-skip contract).  A cached file that
    fails the checksum is discarded and re-resolved, never trusted.
    """
    data_dir = data_dir or _real_data_dir()
    cached = os.path.join(data_dir, _REAL_DATASET_FILE)
    meta = {
        "name": "uci_optdigits",
        "url": _REAL_DATASET_URL,
        "sha256": _REAL_DATASET_SHA256,
        "offline": False,
        "source": "cache",
    }
    if os.path.exists(cached):
        if _sha256(cached) == _REAL_DATASET_SHA256:
            X, y = _parse_digits_csv(cached)
            return X, y, meta
        os.remove(cached)  # corrupt/stale cache: re-resolve
    if download:
        try:
            import urllib.request

            os.makedirs(data_dir, exist_ok=True)
            tmp = cached + ".part"
            with urllib.request.urlopen(
                _REAL_DATASET_URL, timeout=30
            ) as r, open(tmp, "wb") as out:
                out.write(r.read())
            if _sha256(tmp) != _REAL_DATASET_SHA256:
                os.remove(tmp)
                raise OSError("downloaded file failed checksum")
            os.replace(tmp, cached)
            X, y = _parse_digits_csv(cached)
            meta["source"] = "download"
            return X, y, meta
        except Exception:  # noqa: BLE001 — offline is a supported path
            pass
    z = np.load(_SUBSAMPLE, allow_pickle=False)
    meta.update(offline=True, source="committed_subsample")
    return z["X"].astype(np.float64), z["y"].astype(np.int32), meta


def make_blob_data(
    n: int,
    dim: int,
    *,
    n_centers: int | None = None,
    pts_per_center: int = 6250,
    seed: int = 0,
    spread: float = 10.0,
    std: float = 0.4,
    skew: str | None = None,
):
    """Gaussian blobs, uniform or density-skewed; returns ``(X, truth)``.

    ``skew=None``: equal-probability center assignment, one ``std`` —
    the constant-density data every previous round benchmarked.

    ``skew='lognormal'``: cluster POPULATIONS drawn log-normal
    (sigma=1.15 → ~100x span across 64 centers) and per-cluster stds
    uniform in [0.65*std, 1.4*std] (a >2x per-axis density ratio, which
    at 16-D is an astronomically larger volumetric skew).  This stresses
    exactly what uniform data cannot: partition imbalance (pad_waste),
    halo factors around dense cores, pair-budget pressure in crowded
    tiles, and merge depth across population cliffs.  The std range is
    chosen so every cluster stays well above the DBSCAN core threshold
    at the benchmark eps — the generating assignment remains a valid
    oracle (ARI >= 0.99 expected, noise excepted).

    ``truth`` is the (n,) int32 generating assignment.  Memory: X plus
    one int32 row per point; generation is chunked (no n x dim float64
    temps), safe at 50M x 16-D.
    """
    rng = np.random.default_rng(seed)
    if n_centers is None:
        n_centers = max(32, n // pts_per_center)
    centers = rng.uniform(-spread, spread, size=(n_centers, dim)).astype(
        np.float32
    )
    if skew is None:
        assign = rng.integers(0, n_centers, size=n, dtype=np.int32)
        stds = np.full(n_centers, std, np.float32)
    elif skew == "lognormal":
        w = rng.lognormal(mean=0.0, sigma=1.15, size=n_centers)
        p = (w / w.sum()).astype(np.float64)
        # Chunked inverse-CDF sampling: rng.choice materializes int64
        # and is slow at 10M+.
        cdf = np.cumsum(p)
        cdf[-1] = 1.0
        assign = np.empty(n, np.int32)
        for s in range(0, n, _CHUNK):
            e = min(s + _CHUNK, n)
            assign[s:e] = np.searchsorted(
                cdf, rng.random(e - s), side="right"
            ).astype(np.int32)
        stds = rng.uniform(0.65 * std, 1.4 * std, size=n_centers).astype(
            np.float32
        )
    else:
        raise ValueError(f"skew must be None or 'lognormal', got {skew!r}")

    out = centers[assign]
    for s in range(0, n, _CHUNK):
        e = min(s + _CHUNK, n)
        out[s:e] += (
            rng.normal(size=(e - s, dim)) * stds[assign[s:e], None]
        ).astype(np.float32)
    return out, assign


def make_manifold_data(
    n: int,
    dim: int,
    *,
    latent_dim: int = 3,
    n_centers: int = 32,
    seed: int = 0,
    spread: float = 10.0,
    std: float = 0.35,
    ambient_noise: float = 0.02,
):
    """Low-rank embedding-manifold Gaussian mixture (VERDICT r5 Next
    #10); returns ``(X, truth)``.

    Clusters live on a ``latent_dim``-dimensional linear subspace
    embedded in ``dim`` ambient dimensions by a random ORTHONORMAL
    basis, plus small isotropic ambient noise — the correlated
    structure real embedding tables exhibit and isotropic blobs never
    exercise.  This is the adversarial case for Morton-range sharding
    and tile pruning alike: variance concentrates in a rotated
    subspace, so axis-aligned Morton bits and tile bounding boxes are
    all "diagonal" to the data.  The noise/std ratio keeps every
    cluster far above the DBSCAN core threshold at the benchmark eps,
    so the generating assignment remains a valid oracle
    (ARI >= 0.99 expected).  Generation is chunked like
    :func:`make_blob_data`.
    """
    rng = np.random.default_rng(seed)
    latent_dim = max(1, min(int(latent_dim), dim))
    # Orthonormal embedding basis: distances in latent space survive
    # the embedding exactly, so eps keeps its latent meaning.
    basis = np.linalg.qr(
        rng.normal(size=(dim, latent_dim))
    )[0].T.astype(np.float32)  # (latent_dim, dim)
    # Centers with a minimum pairwise separation (greedy thinning of a
    # uniform stream): without it two uniform draws occasionally land
    # close enough for DBSCAN to bridge their clusters at the benchmark
    # eps, which would fail the oracle for a reason that has nothing to
    # do with the code under test.
    min_sep = 8.0 * std
    picked = []
    while len(picked) < n_centers:
        cand = rng.uniform(-spread, spread, size=(4 * n_centers,
                                                  latent_dim))
        for c in cand:
            if len(picked) >= n_centers:
                break
            if not picked or np.min(
                np.linalg.norm(np.asarray(picked) - c, axis=1)
            ) >= min_sep:
                picked.append(c)
    centers = np.asarray(picked, dtype=np.float32)
    assign = rng.integers(0, n_centers, size=n, dtype=np.int32)
    X = np.empty((n, dim), np.float32)
    for s in range(0, n, _CHUNK):
        e = min(s + _CHUNK, n)
        latent = centers[assign[s:e]] + rng.normal(
            size=(e - s, latent_dim)
        ).astype(np.float32) * np.float32(std)
        X[s:e] = latent @ basis
        X[s:e] += (
            rng.normal(size=(e - s, dim)) * ambient_noise
        ).astype(np.float32)
    return X, assign


def make_embedding_data(
    n: int,
    dim: int,
    *,
    latent_dim: int = 8,
    n_centers: int = 24,
    seed: int = 0,
    spread: float = 10.0,
    std: float = 0.35,
    noise: float = 0.02,
):
    """High-d embedding-table stand-in for the sketch-prefilter axis
    (``dim`` in {64, 256, 1024}); returns ``(X, truth)``.

    Low-rank structure plus FULL-RANK ambient noise: cluster geometry
    lives in a ``latent_dim``-dim random orthonormal subspace (like
    :func:`make_manifold_data`) but the noise floor here is large
    enough that every ambient axis carries variance — the regime where
    axis-aligned tile boxes stop pruning (every per-axis gap is small)
    while a k-dim sketch still classifies pairs decisively, i.e. the
    workload the random-projection prefilter exists for.  Centers are
    min-separation thinned exactly like :func:`make_manifold_data`, so
    the generating assignment stays a valid oracle at the benchmark
    eps.  Chunked generation, no n x dim float64 temps.
    """
    rng = np.random.default_rng(seed)
    latent_dim = max(1, min(int(latent_dim), int(dim)))
    basis = np.linalg.qr(
        rng.normal(size=(dim, latent_dim))
    )[0].T.astype(np.float32)  # (latent_dim, dim)
    min_sep = 8.0 * std
    picked = []
    while len(picked) < n_centers:
        cand = rng.uniform(-spread, spread, size=(4 * n_centers,
                                                  latent_dim))
        for c in cand:
            if len(picked) >= n_centers:
                break
            if not picked or np.min(
                np.linalg.norm(np.asarray(picked) - c, axis=1)
            ) >= min_sep:
                picked.append(c)
    centers = np.asarray(picked, dtype=np.float32)
    assign = rng.integers(0, n_centers, size=n, dtype=np.int32)
    X = np.empty((n, dim), np.float32)
    for s in range(0, n, _CHUNK):
        e = min(s + _CHUNK, n)
        latent = centers[assign[s:e]] + rng.normal(
            size=(e - s, latent_dim)
        ).astype(np.float32) * np.float32(std)
        X[s:e] = latent @ basis
        X[s:e] += (
            rng.normal(size=(e - s, dim)) * noise
        ).astype(np.float32)
    return X, assign


def make_separated_blob_data(
    n: int,
    dim: int,
    *,
    n_centers: int = 8,
    std: float = 0.4,
    min_sep: float = 6.0,
    spread: float = 10.0,
    seed: int = 0,
):
    """Gaussian blobs with a GUARANTEED minimum center separation;
    returns ``(X, truth, centers)``.

    The live-update correctness tests compare incremental labels
    against a full refit with ARI == 1.0 — a guarantee that holds
    exactly when no border point sits within eps of two different
    clusters' cores (the one place DBSCAN's own output is
    order-ambiguous).  Rejection-sampling centers to ``min_sep``
    (choose ``min_sep > 2*eps + 6*std``) removes that ambiguity by
    construction, making ARI == 1.0 a sound assertion rather than a
    flaky one.
    """
    rng = np.random.default_rng(seed)
    centers = [rng.uniform(-spread, spread, size=dim)]
    tries = 0
    while len(centers) < n_centers:
        c = rng.uniform(-spread, spread, size=dim)
        if min(np.linalg.norm(c - o) for o in centers) >= min_sep:
            centers.append(c)
        tries += 1
        if tries > 10000:
            raise ValueError(
                f"cannot place {n_centers} centers with min_sep="
                f"{min_sep} inside +-{spread}; loosen one of them"
            )
    centers = np.asarray(centers)
    assign = rng.integers(0, n_centers, size=n)
    X = (centers[assign] + rng.normal(scale=std, size=(n, dim))).astype(
        np.float64
    )
    return X, assign, centers


def ari_vs_truth(labels, truth) -> float:
    """Adjusted Rand index of predicted labels vs the generating
    assignment — the oracle field every benchmark row carries (noise
    points count as their own ARI class, penalizing spurious noise)."""
    from sklearn.metrics import adjusted_rand_score

    return float(adjusted_rand_score(truth, labels))
